"""Step-level checkpoint/resume — beyond-parity auxiliary subsystem.

The reference has NO mid-training checkpointing (SURVEY §5: MLlib's
``setCheckpointInterval`` only guards RDD lineage depth; a crashed
training leaves the EngineInstance in INIT and starts over). Here
training loops save their state pytree every k steps and resume from
the latest *committed* step after a crash.

API shape is deliberately small — ``save``/``restore``/``latest_step``/
``restore_latest`` — so algorithm loops stay one-liner instrumented:

    ckpt = make_checkpointer(dir)
    start, state = ckpt.restore_latest(like=state)
    for step in range(start, n):
        state = update(state)
        ckpt.maybe_save(step + 1, state, every=k)

Two containers behind that contract:

- :class:`Checkpointer` — single-process: Orbax when available, else
  pickle files written via temp-file + atomic rename + fsync (a crash
  mid-save can never leave a truncated pickle that poisons the next
  restore; the stale ``.tmp`` is garbage-collected, not trusted).
- :class:`DistributedCheckpointer` — preemption-safe multihost
  (ISSUE 11, docs/reliability.md): every process writes ONLY its local
  shards of the mesh-sharded pytree, then all processes rendezvous,
  then process 0 writes a ``COMMIT.json`` marker LAST. A step without
  a valid commit marker is *torn* — a process died mid-save — and is
  detected and discarded on restore, falling back to the previous
  committed step. ``kill -9`` at ANY instant loses at most the step in
  flight.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import shutil
from typing import Any, List, Optional, Tuple

import numpy as np

from ..faults import declare, fire

log = logging.getLogger(__name__)

F_SAVE = declare("checkpoint.save",
                 "entry of a checkpoint save (before any bytes hit disk)")
F_COMMIT = declare("checkpoint.commit",
                   "after all shards are written/synced, before the "
                   "commit marker — the torn-checkpoint window")
F_RESTORE = declare("checkpoint.restore", "entry of a checkpoint restore")


class TornCheckpointError(RuntimeError):
    """A step directory exists but is not a committed, readable
    checkpoint (crash mid-save); callers fall back to an earlier step."""


def _fsync_dir(path: str) -> None:
    """Durably record a rename/creation in its directory (best-effort
    on filesystems without directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """temp-file + fsync + atomic rename + directory fsync: after this
    returns, ``path`` durably holds exactly ``data``; a crash at any
    earlier instant leaves the previous content (or nothing) — never a
    truncated file."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


class Checkpointer:
    """Orbax-backed pytree checkpoints under one directory, keyed by
    step. Falls back to pickle when orbax is unavailable (the API is the
    contract, not the container format)."""

    def __init__(self, directory: str, keep: int = 2):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self._mgr = None
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(max_to_keep=keep))
        except Exception as e:  # noqa: BLE001 — pickle fallback
            # refuse a silent restart-from-0: if the directory already
            # holds orbax-format steps (digit-named dirs), degrading to
            # pickle would hide them and lose the resume guarantee
            orbax_steps = [n for n in os.listdir(self.directory)
                           if n.isdigit()
                           and os.path.isdir(os.path.join(self.directory,
                                                          n))]
            if orbax_steps:
                raise RuntimeError(
                    f"{self.directory} holds orbax checkpoints (steps "
                    f"{sorted(orbax_steps)}) but orbax is unavailable "
                    f"({e}); fix the environment instead of silently "
                    f"restarting from scratch")
            log.warning("orbax unavailable (%s); using pickle checkpoints",
                        e)
            self._ocp = None

    # -- orbax path --------------------------------------------------------
    def save(self, step: int, state: Any) -> None:
        fire(F_SAVE, step=step)
        if self._mgr is not None:
            # async: only wait for the PREVIOUS save before issuing this
            # one, so writes overlap the next training step; close()
            # drains the last one
            self._mgr.wait_until_finished()
            self._mgr.save(step, args=self._ocp.args.StandardSave(state))
            return
        from .persistence import to_host

        path = os.path.join(self.directory, f"step_{step}.pkl")
        payload = pickle.dumps(to_host(state), protocol=4)
        fire(F_COMMIT, step=step)
        _atomic_write(path, payload)
        self._prune_pickles()

    def restore(self, step: int, like: Optional[Any] = None) -> Any:
        fire(F_RESTORE, step=step)
        if self._mgr is not None:
            if like is not None:
                return self._mgr.restore(
                    step, args=self._ocp.args.StandardRestore(like))
            return self._mgr.restore(step)
        with open(os.path.join(self.directory, f"step_{step}.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def restore_latest(self, like: Optional[Any] = None,
                       max_step: Optional[int] = None
                       ) -> Tuple[int, Optional[Any]]:
        """``(step, state)`` of the newest RESTORABLE checkpoint at or
        below ``max_step`` — a torn/corrupt step (crash mid-save, a
        truncated container) is logged and skipped, falling back to the
        previous committed one; ``(0, None)`` when nothing restores."""
        return _restore_latest(self, like, max_step)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def all_steps(self) -> list:
        if self._mgr is not None:
            return sorted(self._mgr.all_steps())
        return sorted(self._pickle_steps())

    # -- run metadata (fingerprint guard against foreign checkpoints) ------
    def set_metadata(self, meta: dict) -> None:
        _atomic_write(os.path.join(self.directory, "run_metadata.json"),
                      json.dumps(meta).encode("utf-8"))

    def get_metadata(self) -> Optional[dict]:
        path = os.path.join(self.directory, "run_metadata.json")
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def maybe_save(self, step: int, state: Any, every: int) -> bool:
        """Save when ``step`` is a multiple of ``every`` (0 = never)."""
        if every and step % every == 0:
            self.save(step, state)
            return True
        return False

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()

    # -- pickle fallback helpers -------------------------------------------
    def _pickle_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and name.endswith(".pkl"):
                try:
                    out.append(int(name[5:-4]))
                except ValueError:
                    pass
        return out

    def _prune_pickles(self) -> None:
        steps = sorted(self._pickle_steps())
        for s in steps[: -self.keep]:
            os.remove(os.path.join(self.directory, f"step_{s}.pkl"))


def _restore_latest(ckpt, like, max_step) -> Tuple[int, Optional[Any]]:
    """Shared newest-restorable-step walk (desc order, torn steps
    skipped) for both checkpointer flavors."""
    steps = [s for s in ckpt.all_steps()
             if max_step is None or s <= max_step]
    for s in sorted(steps, reverse=True):
        try:
            return int(s), ckpt.restore(s, like=like)
        except Exception as e:  # noqa: BLE001 — torn/corrupt step:
            # fall back to the previous committed one
            log.warning("checkpoint step %s unreadable (%s); falling "
                        "back to the previous committed step", s, e)
    return 0, None


# ---------------------------------------------------------------------------
# Preemption-safe distributed checkpointing (ISSUE 11)
# ---------------------------------------------------------------------------

_COMMIT = "COMMIT.json"


def _serialize_index(index) -> list:
    """A shard's global-array slice tuple as JSON ``[[start, stop], …]``
    (None start/stop normalized against the dimension elsewhere — JAX
    addressable-shard indices are always concrete slices)."""
    out = []
    for sl in index:
        out.append([None if sl.start is None else int(sl.start),
                    None if sl.stop is None else int(sl.stop)])
    return out


def _norm_index(index, shape) -> tuple:
    """Hashable normalized form of a shard index for matching saved
    shards to the restore sharding's addressable devices."""
    out = []
    for i, sl in enumerate(index):
        start = 0 if sl[0] is None else int(sl[0])
        stop = int(shape[i]) if sl[1] is None else int(sl[1])
        out.append((start, stop))
    return tuple(out)


def _is_jax_array(leaf: Any) -> bool:
    try:
        import jax

        return isinstance(leaf, jax.Array)
    except Exception:  # noqa: BLE001 — no jax: nothing is a jax array
        return False


class DistributedCheckpointer:
    """Per-process sharded checkpoints of mesh-sharded pytrees with a
    rendezvous commit marker (module docstring). Layout::

        <dir>/step_00000003/shard_p0.npz   # process 0's local shards
        <dir>/step_00000003/shard_p0.json  # its per-leaf shard index
        <dir>/step_00000003/shard_p1.npz
        <dir>/step_00000003/shard_p1.json
        <dir>/step_00000003/COMMIT.json    # written LAST, by process 0

    The directory must be shared across processes (NFS/GCS on a pod;
    one tmpdir in the CI drill). Replicated leaves (plain numpy, or a
    fully-replicated jax.Array) are written once, by the process that
    owns replica 0 of each shard; restore reads ANY process's files, so
    process/shard layout may be re-derived from the ``like`` pytree's
    shardings as long as every saved shard index is covered.
    """

    def __init__(self, directory: str, keep: int = 2,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        if process_index is None or process_count is None:
            try:
                import jax

                process_index = jax.process_index()
                process_count = jax.process_count()
            except Exception:  # noqa: BLE001 — no backend: single
                process_index, process_count = 0, 1
        self.pid = int(process_index)
        self.n_proc = int(process_count)

    # -- rendezvous --------------------------------------------------------
    def _barrier(self, tag: str) -> None:
        if self.n_proc <= 1:
            return
        from ..parallel.multihost import barrier

        barrier(f"ckpt:{os.path.basename(self.directory)}:{tag}")

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step):08d}")

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Any) -> None:
        import jax

        fire(F_SAVE, step=step)
        step_dir = self._step_dir(step)
        os.makedirs(step_dir, exist_ok=True)
        leaves, _ = jax.tree_util.tree_flatten(state)
        arrays: dict = {}
        index: List[dict] = []
        for i, leaf in enumerate(leaves):
            if _is_jax_array(leaf) and getattr(leaf, "sharding", None) \
                    is not None and not leaf.is_fully_replicated:
                for shard in leaf.addressable_shards:
                    if shard.replica_id != 0:
                        continue  # another device holds the same rows
                    key = f"l{i}_s{len(index)}"
                    arrays[key] = np.asarray(shard.data)
                    index.append({
                        "leaf": i, "key": key,
                        "index": _serialize_index(shard.index),
                        "shape": [int(d) for d in leaf.shape]})
            else:
                # replicated/host leaf: ONE writer (the lowest process)
                if self.pid == 0:
                    key = f"l{i}_full"
                    arrays[key] = np.asarray(leaf)
                    index.append({"leaf": i, "key": key, "index": None})
        # npz then json, each atomic+fsynced; the json names the npz so
        # a reader never trusts a shard file without its manifest
        import io

        buf = io.BytesIO()
        np.savez(buf, **arrays)
        npz_name = f"shard_p{self.pid}.npz"
        _atomic_write(os.path.join(step_dir, npz_name), buf.getvalue())
        _atomic_write(
            os.path.join(step_dir, f"shard_p{self.pid}.json"),
            json.dumps({"process": self.pid, "npz": npz_name,
                        "entries": index}).encode("utf-8"))
        # every process's shards durable BEFORE anyone may commit
        self._barrier(f"save:{step}")
        fire(F_COMMIT, step=step)
        if self.pid == 0:
            _atomic_write(
                os.path.join(step_dir, _COMMIT),
                json.dumps({
                    "step": int(step),
                    "processes": self.n_proc,
                    "manifests": [f"shard_p{p}.json"
                                  for p in range(self.n_proc)],
                }).encode("utf-8"))
            _fsync_dir(self.directory)
        # nobody races ahead (and prunes/overwrites) before the commit
        # marker exists
        self._barrier(f"commit:{step}")
        if self.pid == 0:
            self._prune()

    # -- restore -----------------------------------------------------------
    def _read_commit(self, step: int) -> dict:
        path = os.path.join(self._step_dir(step), _COMMIT)
        try:
            with open(path, "r", encoding="utf-8") as f:
                commit = json.load(f)
        except (OSError, ValueError) as e:
            raise TornCheckpointError(
                f"step {step}: no valid commit marker ({e}) — save was "
                f"interrupted; discarding") from e
        return commit

    def restore(self, step: int, like: Optional[Any] = None) -> Any:
        """Rebuild the pytree for THIS process: sharded leaves are
        reassembled from the saved shards matching ``like``'s sharding
        (device_put per local shard), replicated leaves come back as
        host numpy. Raises :class:`TornCheckpointError` on a step with
        a missing/invalid commit marker or missing shard data."""
        import jax

        fire(F_RESTORE, step=step)
        if like is None:
            raise ValueError("DistributedCheckpointer.restore needs "
                             "like= (the tree/sharding template)")
        commit = self._read_commit(step)
        step_dir = self._step_dir(step)
        # leaf → {normalized index or None → np.ndarray}
        shards: dict = {}
        for manifest_name in commit["manifests"]:
            try:
                with open(os.path.join(step_dir, manifest_name),
                          "r", encoding="utf-8") as f:
                    manifest = json.load(f)
                data = np.load(os.path.join(step_dir, manifest["npz"]))
            except (OSError, ValueError) as e:
                raise TornCheckpointError(
                    f"step {step}: shard manifest {manifest_name} "
                    f"unreadable ({e})") from e
            for entry in manifest["entries"]:
                per_leaf = shards.setdefault(int(entry["leaf"]), {})
                if entry["index"] is None:
                    per_leaf[None] = data[entry["key"]]
                else:
                    per_leaf[_norm_index(entry["index"],
                                         entry["shape"])] = \
                        data[entry["key"]]
        leaves, treedef = jax.tree_util.tree_flatten(like)
        out: List[Any] = []
        for i, leaf in enumerate(leaves):
            per_leaf = shards.get(i)
            if per_leaf is None:
                raise TornCheckpointError(
                    f"step {step}: leaf {i} missing from every shard "
                    f"manifest")
            if _is_jax_array(leaf) and not leaf.is_fully_replicated:
                sharding = leaf.sharding
                idx_map = sharding.addressable_devices_indices_map(
                    leaf.shape)
                pieces = []
                for dev, idx in idx_map.items():
                    want = _norm_index(_serialize_index(idx), leaf.shape)
                    if want not in per_leaf:
                        raise TornCheckpointError(
                            f"step {step}: leaf {i} shard {want} not in "
                            f"the saved set (process/mesh layout "
                            f"changed?)")
                    pieces.append(jax.device_put(per_leaf[want], dev))
                out.append(jax.make_array_from_single_device_arrays(
                    leaf.shape, sharding, pieces))
            else:
                full = per_leaf.get(None)
                if full is None:
                    raise TornCheckpointError(
                        f"step {step}: replicated leaf {i} missing")
                out.append(full)
        return jax.tree_util.tree_unflatten(treedef, out)

    def restore_latest(self, like: Optional[Any] = None,
                       max_step: Optional[int] = None
                       ) -> Tuple[int, Optional[Any]]:
        """``(step, state)`` of the newest COMMITTED restorable step at
        or below ``max_step``; torn steps are skipped (and every
        process falls back identically — ``all_steps`` only lists
        committed markers, so the walk is deterministic across the
        mesh); ``(0, None)`` when none restores."""
        return _restore_latest(self, like, max_step)

    def discard_torn(self) -> List[int]:
        """Delete step dirs without a valid commit marker (process 0
        only — others observe); returns the discarded step numbers."""
        torn = []
        for name in sorted(os.listdir(self.directory)):
            if not name.startswith("step_"):
                continue
            try:
                step = int(name[5:])
            except ValueError:
                continue
            try:
                self._read_commit(step)
            except TornCheckpointError:
                torn.append(step)
                if self.pid == 0:
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
        return torn

    # -- bookkeeping -------------------------------------------------------
    def all_steps(self) -> list:
        """Committed steps only — an uncommitted (torn) dir is not a
        checkpoint."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and os.path.exists(
                    os.path.join(self.directory, name, _COMMIT)):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def maybe_save(self, step: int, state: Any, every: int) -> bool:
        if every and step % every == 0:
            self.save(step, state)
            return True
        return False

    def set_metadata(self, meta: dict) -> None:
        if self.pid == 0:
            _atomic_write(
                os.path.join(self.directory, "run_metadata.json"),
                json.dumps(meta).encode("utf-8"))
        self._barrier("metadata")

    def get_metadata(self) -> Optional[dict]:
        path = os.path.join(self.directory, "run_metadata.json")
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def close(self) -> None:
        pass

    def _prune(self) -> None:
        steps = self.all_steps()
        for s in steps[: -self.keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


def make_checkpointer(directory: str, keep: int = 2):
    """The factory training loops call: the distributed container when
    this is a multi-process run (or ``PTPU_DIST_CKPT=1`` forces it —
    drills and tests exercise the sharded layout single-process), else
    the single-process :class:`Checkpointer`."""
    force = os.environ.get("PTPU_DIST_CKPT", "") == "1"
    n = 1
    try:
        import jax

        n = jax.process_count()
    except Exception:  # noqa: BLE001 — no backend yet: single-process
        pass
    if force or n > 1:
        return DistributedCheckpointer(directory, keep=keep)
    return Checkpointer(directory, keep=keep)
