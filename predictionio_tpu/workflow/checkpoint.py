"""Step-level checkpoint/resume — beyond-parity auxiliary subsystem.

The reference has NO mid-training checkpointing (SURVEY §5: MLlib's
``setCheckpointInterval`` only guards RDD lineage depth; a crashed
training leaves the EngineInstance in INIT and starts over). Here
training loops save their state pytree every k steps through Orbax and
resume from the latest step after a crash.

API shape is deliberately small — ``save``/``restore``/``latest_step`` —
so algorithm loops stay one-liner instrumented:

    ckpt = Checkpointer(dir)
    start = ckpt.latest_step() or 0
    state = ckpt.restore(start, like=state) if start else state
    for step in range(start, n):
        state = update(state)
        ckpt.maybe_save(step + 1, state, every=k)
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

log = logging.getLogger(__name__)


class Checkpointer:
    """Orbax-backed pytree checkpoints under one directory, keyed by
    step. Falls back to pickle when orbax is unavailable (the API is the
    contract, not the container format)."""

    def __init__(self, directory: str, keep: int = 2):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self._mgr = None
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(max_to_keep=keep))
        except Exception as e:  # noqa: BLE001 — pickle fallback
            # refuse a silent restart-from-0: if the directory already
            # holds orbax-format steps (digit-named dirs), degrading to
            # pickle would hide them and lose the resume guarantee
            orbax_steps = [n for n in os.listdir(self.directory)
                           if n.isdigit()
                           and os.path.isdir(os.path.join(self.directory,
                                                          n))]
            if orbax_steps:
                raise RuntimeError(
                    f"{self.directory} holds orbax checkpoints (steps "
                    f"{sorted(orbax_steps)}) but orbax is unavailable "
                    f"({e}); fix the environment instead of silently "
                    f"restarting from scratch")
            log.warning("orbax unavailable (%s); using pickle checkpoints",
                        e)
            self._ocp = None

    # -- orbax path --------------------------------------------------------
    def save(self, step: int, state: Any) -> None:
        if self._mgr is not None:
            # async: only wait for the PREVIOUS save before issuing this
            # one, so writes overlap the next training step; close()
            # drains the last one
            self._mgr.wait_until_finished()
            self._mgr.save(step, args=self._ocp.args.StandardSave(state))
            return
        import pickle

        from .persistence import to_host

        path = os.path.join(self.directory, f"step_{step}.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(to_host(state), f, protocol=4)
        os.replace(tmp, path)
        self._prune_pickles()

    def restore(self, step: int, like: Optional[Any] = None) -> Any:
        if self._mgr is not None:
            if like is not None:
                return self._mgr.restore(
                    step, args=self._ocp.args.StandardRestore(like))
            return self._mgr.restore(step)
        import pickle

        with open(os.path.join(self.directory, f"step_{step}.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def all_steps(self) -> list:
        if self._mgr is not None:
            return sorted(self._mgr.all_steps())
        return sorted(self._pickle_steps())

    # -- run metadata (fingerprint guard against foreign checkpoints) ------
    def set_metadata(self, meta: dict) -> None:
        import json

        path = os.path.join(self.directory, "run_metadata.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def get_metadata(self) -> Optional[dict]:
        import json

        path = os.path.join(self.directory, "run_metadata.json")
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def maybe_save(self, step: int, state: Any, every: int) -> bool:
        """Save when ``step`` is a multiple of ``every`` (0 = never)."""
        if every and step % every == 0:
            self.save(step, state)
            return True
        return False

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()

    # -- pickle fallback helpers -------------------------------------------
    def _pickle_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and name.endswith(".pkl"):
                try:
                    out.append(int(name[5:-4]))
                except ValueError:
                    pass
        return out

    def _prune_pickles(self) -> None:
        steps = sorted(self._pickle_steps())
        for s in steps[: -self.keep]:
            os.remove(os.path.join(self.directory, f"step_{s}.pkl"))
