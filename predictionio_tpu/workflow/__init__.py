"""Workflow layer: train/eval/deploy drivers and model persistence."""

from .core import (
    get_latest_completed,
    load_models_for_deploy,
    run_evaluation,
    run_train,
)
from .persistence import dumps_models, loads_models, to_device, to_host

__all__ = [
    "dumps_models",
    "get_latest_completed",
    "load_models_for_deploy",
    "loads_models",
    "run_evaluation",
    "run_train",
    "to_device",
    "to_host",
]
