"""Model persistence: device pytrees ⇄ durable blobs.

The reference Kryo-serialized whole model Seqs into the MODELDATA store
(``workflow/CreateServer.scala:62-76``, ``workflow/CoreWorkflow.scala:76-81``)
and inverted them at deploy (``CreateServer.scala:202-206``). Here models
are pytrees of ``jax.Array``s: ``to_host`` maps device arrays to numpy for
pickling, ``to_device`` moves them back (re-sharding happens lazily when the
serving/eval code puts them on a mesh). Custom persistence (the reference's
``PersistentModel``) is signaled with a ``PersistentModelManifest`` instead.
"""

from __future__ import annotations

import functools
import io
import pickle
from typing import Any, List

import jax
import numpy as np


@functools.lru_cache(maxsize=8)
def _replicator(mesh):
    """One compiled identity-with-replication program per mesh — a
    fresh ``jax.jit(lambda ...)`` per leaf would recompile the
    all-gather for every sharded leaf of every persist."""
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.jit(lambda a: a,
                   out_shardings=NamedSharding(mesh, PartitionSpec()))


def _leaf_to_host(x):
    if not isinstance(x, jax.Array):
        return x
    if x.is_fully_addressable:
        return np.asarray(x)
    # multihost: a globally-sharded array has remote shards — replicate
    # through the compiled program (XLA all-gather over the fabric),
    # then read the local copy. COLLECTIVE: every process must reach
    # this point (run_train is SPMD — all processes persist together,
    # only process 0 writes the blob).
    rep = _replicator(x.sharding.mesh)(x)
    return np.asarray(rep.addressable_data(0))


def to_host(model: Any) -> Any:
    """Replace every jax.Array leaf with numpy (pickle-safe); multihost
    sharded leaves are replicated collectively first."""
    return jax.tree.map(_leaf_to_host, model)


def to_device(model: Any) -> Any:
    """Identity by default: numpy leaves are device-put lazily by jit at
    first use, which lets the serving path choose shardings."""
    return model


def dumps_models(models: List[Any]) -> bytes:
    """Serialize the per-algorithm model list to one blob (the Kryo-blob
    role)."""
    buf = io.BytesIO()
    pickle.dump([to_host(m) for m in models], buf, protocol=4)
    return buf.getvalue()


def loads_models(blob: bytes) -> List[Any]:
    return pickle.loads(blob)
