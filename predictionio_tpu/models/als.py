"""Alternating least squares on a TPU mesh — explicit and implicit.

The north-star algorithm (SURVEY §7 hard part 1): the role MLlib ALS plays
for the reference's recommendation templates
(``tests/pio_tests/engines/recommendation-engine/src/main/scala/
ALSAlgorithm.scala:51-93`` explicit, ``examples/scala-parallel-
similarproduct/.../ALSAlgorithm.scala`` trainImplicit), re-designed
ALX-style (arXiv 2112.02194) instead of translating MLlib's block
partitioning + shuffle joins:

- Both factor matrices live **row-sharded across all mesh devices**; the
  per-row normal equations are built from padded per-row histories
  (static shapes, no ragged data on device) and solved as one batched
  Cholesky on the MXU.
- The rank×rank Gramian and the cross-shard factor gathers lower to XLA
  collectives (all-reduce / all-gather) over ICI — no hand-written
  NCCL/shuffle analogue.
- MLlib semantic parity: ALS-WR regularization (λ scaled by each row's
  rating count) and Hu-Koren-Volinsky implicit confidence
  c = 1 + alpha·r with the fixed-side Gramian as the preference-0
  baseline term.

One API covers the reference's L/P split: mesh=None (or 1 device) is the
local path, mesh of N shards the same code.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import numerics as _numerics
from ..ops.ragged import BucketedHistories, PaddedHistories, SplitHistories
from ..ops.solve import gramian, solve_spd_batch
from ..parallel.mesh import rows_spec
from ..utils.platform import enable_compilation_cache

@dataclass(frozen=True)
class ALSParams:
    """Hyperparameters, name-compatible with the reference template's
    engine.json (rank, numIterations, lambda, seed — ``tests/pio_tests/
    engines/recommendation-engine/engine.json``) plus the implicit-ALS
    knobs of the similar-product template."""

    rank: int = 10
    num_iterations: int = 10
    #: regularization — "lambda" in the reference's engine.json; the wire
    #: alias keeps those variant files working verbatim
    reg: float = field(default=0.01,
                       metadata={"aliases": ("lambda", "lambda_")})
    alpha: float = 1.0         # implicit confidence scale
    implicit_prefs: bool = False
    seed: int = 3
    max_history: Optional[int] = None  # cap padded history length
    scale_reg_by_count: bool = True    # ALS-WR λ·n_u scaling (MLlib parity)
    block_rows: Optional[int] = None   # per-device rows per update block
    #: "bfloat16" runs the normal-equation einsums on the MXU in bf16
    #: with f32 accumulation (the TPU-native mixed-precision idiom);
    #: factors and solves stay f32.
    matmul_dtype: str = "float32"
    #: "bfloat16" gathers each half-iteration's factor rows from a
    #: bf16 SHADOW of the (still-f32) factor table; master weights,
    #: gram accumulation and solves stay f32. Measured round 4 on a
    #: v5e: the f32 table (138k×64 = 35MB) is too big for XLA to keep
    #: VMEM-resident alongside the Pallas solve's scratch, so the 20M
    #: row gathers ran from HBM at ~6× the VMEM-resident cost — the
    #: whole-iteration bound. The 17.6MB shadow stays VMEM-staged:
    #: 1.98× per-iteration speedup for an ~0.4% relative perturbation
    #: of the normal-equation INPUTS (quality-checked by
    #: tests/test_als.py::TestGatherDtype).
    gather_dtype: str = "float32"
    #: Weighted-gram realization: "einsum" (baseline batched matmul),
    #: "pair" (two rank-r systems packed per 128x128 MXU tile —
    #: ``ops/gram.py``), "fused" (the Pallas gather+Gramian kernel,
    #: ``ops/fused_gram.py`` — the gathered [B, L, r] temp never lands
    #: in HBM; on non-TPU backends this runs the kernel in interpret
    #: mode, a debugging path), or "auto" (the persistent shape-keyed
    #: autotune table, support-gated so "fused" never resolves where
    #: the kernel cannot lower).
    gram_mode: str = "auto"
    #: History layout. "pad": one [n_rows, L] padded matrix per side
    #: (entries beyond L are DROPPED — round-1 semantics). "bucket":
    #: power-of-two length buckets, drop-free at ≤2× padding with MXU-deep
    #: contractions — the default drop-free layout. "split": rows longer
    #: than L become virtual rows scatter-added back (drop-free but the
    #: duplicate-index scatter serializes on TPU; kept for comparison).
    #: "auto": pad when nothing would be dropped (or when max_history
    #: explicitly caps), bucket otherwise.
    history_mode: str = "auto"

    def __post_init__(self):
        if self.matmul_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"matmul_dtype must be 'float32' or 'bfloat16', got "
                f"{self.matmul_dtype!r}")
        if self.gather_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"gather_dtype must be 'float32' or 'bfloat16', got "
                f"{self.gather_dtype!r}")
        if self.history_mode not in ("auto", "pad", "split", "bucket"):
            raise ValueError(
                f"history_mode must be 'auto', 'pad', 'split' or "
                f"'bucket', got {self.history_mode!r}")
        if self.gram_mode not in ("auto", "einsum", "pair", "fused"):
            raise ValueError(
                f"gram_mode must be 'auto', 'einsum', 'pair' or "
                f"'fused', got {self.gram_mode!r}")


@jax.tree_util.register_dataclass
@dataclass
class ALSModel:
    """Factor matrices (possibly padded past n_users/n_items for even
    sharding) + the id indexation back to entity-id strings.

    Registered as a pytree (factors are children; ids/params are static
    metadata) so persistence's ``jax.tree.map(to_host)`` reaches the
    device arrays inside."""

    user_factors: jax.Array = field(metadata=dict(static=False))
    item_factors: jax.Array = field(metadata=dict(static=False))
    n_users: int = field(metadata=dict(static=True))
    n_items: int = field(metadata=dict(static=True))
    user_ids: Optional[object] = field(default=None,
                                       metadata=dict(static=True))
    item_ids: Optional[object] = field(default=None,
                                       metadata=dict(static=True))
    params: ALSParams = field(default_factory=ALSParams,
                              metadata=dict(static=True))
    #: serving mesh when the factor tables are row-sharded
    #: (:func:`shard_model`); None for host/single-device models. Set
    #: at DEPLOY time only — persisted models never carry a mesh (a
    #: Mesh binds to live devices and must not enter the blob store).
    mesh: Optional[Mesh] = field(default=None, metadata=dict(static=True))


@dataclass(frozen=True)
class RatingsCOO:
    """Integer-indexed rating triples (host side)."""

    users: np.ndarray   # int32 [nnz]
    items: np.ndarray   # int32 [nnz]
    ratings: np.ndarray  # float32 [nnz]
    n_users: int
    n_items: int


def _resolves_fused(gram: str, rank: int, bf16: bool) -> bool:
    """Whether ``gram`` lands on the fused Pallas kernel at trace time:
    explicitly, or via the support-gated autotune table ("auto" never
    resolves to fused where the kernel cannot lower)."""
    if gram == "fused":
        return True
    if gram != "auto":
        return False
    from ..ops.gram_autotune import best_mode

    return best_mode(rank, bf16=bf16) == "fused"


def resolved_gram_mode(params: "ALSParams") -> str:
    """The concrete gram realization ``params`` trains with on the
    attached backend — the label value of the ``pio_gram_mode`` info
    gauge (docs/observability.md)."""
    if params.gram_mode != "auto":
        return params.gram_mode
    from ..ops.gram_autotune import best_mode

    return best_mode(params.rank,
                     bf16=(params.matmul_dtype == "bfloat16"))


def _fused_lhs(table: jax.Array, indices: jax.Array, wa: jax.Array,
               wb: jax.Array, mesh: Optional[Mesh]):
    """The fused-kernel realization of :func:`_lhs_fn`: gather and
    Gramian in one Pallas launch (``ops/fused_gram.py``) — the
    ``[d, B, L, r]`` temp never exists. Under a mesh the kernel runs on
    each device's LOCAL rows via shard_map: the fixed table enters
    replicated (the same all-gather the GSPMD gather pays), the
    index/weight blocks and both outputs stay row-sharded."""
    from ..ops.fused_gram import fused_gram_dispatch

    r = table.shape[-1]
    L = indices.shape[-1]

    def flat(tab, idx, a, b2):
        A, bb = fused_gram_dispatch(tab, idx.reshape(-1, L),
                                    a.reshape(-1, L), b2.reshape(-1, L))
        return (A.reshape(idx.shape[:-1] + (r, r)),
                bb.reshape(idx.shape[:-1] + (r,)))

    if mesh is None:
        return flat(table, indices, wa, wb)
    from ..parallel.collectives import shard_map_compat

    spec = rows_spec(mesh)
    fn = shard_map_compat(flat, mesh,
                          in_specs=(P(), spec, spec, spec),
                          out_specs=(spec, spec), check=False)
    return fn(table, indices, wa, wb)


def _lhs_fn(table: jax.Array, indices: jax.Array, wa: jax.Array,
            wb: jax.Array, *, gram: str, bf16: bool,
            mesh: Optional[Mesh] = None):
    """Per-row normal-equation build — the ONE place the factor gather
    exists: ``A = Σ_l wa·f fᵀ`` and the fused RHS ``b = Σ_l wb·f`` over
    ``f = table[indices]``. ``table`` is the f32 factors or the bf16
    shadow (:func:`_shadow_lhs_fn` casts for callers that have not);
    weights arrive pre-masked so padding slots contribute exactly zero.

    ``gram_mode="fused"`` (and "auto" resolving to it) routes to the
    Pallas fused gather+Gramian kernel and never materializes the
    ``[d, B, L, r]`` gather temp in HBM. Every other mode gathers and
    dispatches to ``ops/gram.py`` exactly as before. Under a mesh the
    kernel covers row-sharded blocks; L-axis-sharded skinny buckets
    keep the einsum path, whose contraction over L GSPMD turns into
    per-device partial Gramians + an all-reduce."""
    if _resolves_fused(gram, table.shape[-1], bf16) \
            and (mesh is None or indices.shape[0] == mesh.devices.size):
        return _fused_lhs(table, indices, wa, wb, mesh)
    from ..ops.gram import gram_dispatch

    # gather_dtype="bfloat16": F stays bf16 INTO the einsums — the
    # upcast to f32 happens inside each dot's fusion (exact: the values
    # are already bf16-quantized) instead of as a standalone convert
    # materializing a second full-size F (measured 5.2ms per block in
    # the round-4 trace). Accumulation/solve stay f32 via promotion.
    # ptpu: allow[materialized-gather] — bounded by _auto_block_rows'
    # ~1GB block budget, and eliminated entirely under gram_mode="fused"
    F = table[indices]  # [d, B, L, r] — cross-shard gather under a mesh
    A = gram_dispatch(F, wa, mode=gram, bf16=bf16)
    # F can be the bf16 shadow: keep the RHS accumulation f32, matching
    # the Gramian side (ops/gram.py contract) — without this the Σ_l
    # wb·f sum runs at bf16 and fold-in solves drift
    b = jnp.einsum("...lr,...l->...r", F, wb,
                   preferred_element_type=jnp.float32)
    return A, b


def _shadow_lhs_fn(table_f32: jax.Array, indices: jax.Array,
                   wa: jax.Array, wb: jax.Array, *, gram: str,
                   bf16: bool, mesh: Optional[Mesh] = None):
    """:func:`_lhs_fn` over the bf16 SHADOW of an f32 table (the
    ``ALSParams.gather_dtype="bfloat16"`` wire): rows travel HBM→MXU
    (or HBM→VMEM, fused) as bf16, accumulation stays f32. The
    half-iteration impls pre-cast ONCE per half-step so every block
    shares one shadow buffer; this entry is for callers without that
    amortization (tests, one-shot solves)."""
    return _lhs_fn(table_f32.astype(jnp.bfloat16), indices, wa, wb,
                   gram=gram, bf16=bf16, mesh=mesh)


@functools.partial(jax.jit, static_argnames=("implicit", "scale_reg",
                                             "bf16", "gram", "mesh"))
def _update_block(fixed: jax.Array, G, indices: jax.Array,
                  values: jax.Array, counts: jax.Array, reg: float,
                  alpha: float, implicit: bool, scale_reg: bool,
                  bf16: bool = False, gram: str = "auto",
                  mesh: Optional[Mesh] = None) -> jax.Array:
    """Recompute one block of rows, holding ``fixed`` constant.

    fixed: [m, r] (flat, row-sharded); G: [r, r] Gramian of ``fixed`` (only
    for implicit); indices/values: [d, B, L]; counts: [d, B] with leading
    axis sharded across all devices → new factors [d, B, r], same sharding.
    Padding entries carry value 0 and index 0; masks keep them inert.
    ``mesh`` (static) lets the fused path run its kernel per device on
    local rows; the einsum/pair paths ignore it (GSPMD places them).
    """
    r = fixed.shape[-1]
    L = indices.shape[-1]
    valid = (jnp.arange(L)[None, None, :]
             < counts[:, :, None]).astype(jnp.float32)
    if implicit:
        # Hu-Koren-Volinsky: c = 1 + alpha·r, preference p=1 on observed.
        # A = G + Σ (c-1)·f fᵀ (G = FᵀF baseline over *all* items),
        # b = Σ c·f on observed entries.
        wa = alpha * values * valid              # c - 1, 0 at padding
        wb = (wa + 1.0) * valid
    else:
        wa = valid
        wb = values * valid
    A, b = _lhs_fn(fixed, indices, wa, wb, gram=gram, bf16=bf16,
                   mesh=mesh)
    if implicit:
        # G is added AFTER the kernel/einsum output on purpose: the
        # blocks' normal-equation build has no data dependence on the
        # fixed-side Gramian, so its (mesh) all-reduce overlaps the
        # first block's gather instead of gating it
        A = G[None, None] + A

    reg_n = reg * jnp.maximum(counts.astype(jnp.float32), 1.0) if scale_reg \
        else jnp.full(counts.shape, reg, dtype=jnp.float32)
    A = A + reg_n[..., None, None] * jnp.eye(r, dtype=A.dtype)
    return solve_spd_batch(A, b)


_gramian_jit = jax.jit(gramian)


def _fixed_gramian(fixed: jax.Array, mesh: Optional[Mesh], gram: str,
                   bf16: bool):
    """Implicit-path baseline Gramian FᵀF of the fixed side. Under a
    mesh on the fused path it is computed as an EXPLICIT per-shard
    partial + ICI psum (``parallel/collectives.gramian_allreduce``)
    that nothing in any block's kernel depends on: blocks add G to
    their kernel output last (:func:`_update_block`), so the all-reduce
    rides under the next virtual-row block's gather/kernel launch
    instead of serializing the half-iteration on it — the ALX overlap
    (arXiv 2112.02194). Elsewhere it stays the plain einsum whose
    collective GSPMD derives."""
    if mesh is not None and _resolves_fused(gram, fixed.shape[-1], bf16):
        from ..parallel.collectives import gramian_allreduce

        return gramian_allreduce(fixed, mesh)
    # jitted (compile-once) for the eager split path; inlined like the
    # plain einsum when traced inside a half-step program
    return _gramian_jit(fixed)


@functools.partial(jax.jit, static_argnames=("implicit", "bf16",
                                             "gram", "mesh"),
                   donate_argnums=(5, 6))
def _partials_block(fixed: jax.Array, indices: jax.Array,
                    values: jax.Array, counts: jax.Array,
                    row_ids: jax.Array, A_acc: jax.Array,
                    b_acc: jax.Array, alpha: float, implicit: bool,
                    bf16: bool = False, gram: str = "auto",
                    mesh: Optional[Mesh] = None):
    """Split-mode half of :func:`_update_block`: per-VIRTUAL-row partials
    Σ w·ffᵀ and Σ w·f, scatter-added onto the owning real rows.
    Sentinel/padding virtual rows contribute exactly zero (their valid
    mask is all-zero), so out-of-range ids are safe under mode="drop"."""
    r = fixed.shape[-1]
    L = indices.shape[-1]
    valid = (jnp.arange(L)[None, None, :]
             < counts[:, :, None]).astype(jnp.float32)
    if implicit:
        wa = alpha * values * valid
        wb = (wa + 1.0) * valid
    else:
        wa = valid
        wb = values * valid
    A_v, b_v = _lhs_fn(fixed, indices, wa, wb, gram=gram, bf16=bf16,
                       mesh=mesh)
    ids = row_ids.reshape(-1)
    A_acc = A_acc.at[ids].add(A_v.reshape(-1, r, r), mode="drop")
    b_acc = b_acc.at[ids].add(b_v.reshape(-1, r), mode="drop")
    return A_acc, b_acc


@functools.partial(jax.jit, static_argnames=("implicit", "scale_reg"))
def _solve_accumulated(A_acc: jax.Array, b_acc: jax.Array,
                       G, real_counts: jax.Array, reg: float,
                       implicit: bool, scale_reg: bool) -> jax.Array:
    """Finish a split-mode half-step: implicit baseline Gramian (added
    once per real row, after accumulation), ALS-WR regularization from
    TRUE row totals, one batched SPD solve. Rows with no ratings keep
    b=0 and solve to exactly 0 — identical to the pad path's padding."""
    r = A_acc.shape[-1]
    A = A_acc + G[None] if implicit else A_acc
    reg_n = reg * jnp.maximum(real_counts.astype(jnp.float32), 1.0) \
        if scale_reg else jnp.full(real_counts.shape, reg,
                                   dtype=jnp.float32)
    A = A + reg_n[:, None, None] * jnp.eye(r, dtype=A.dtype)
    return solve_spd_batch(A, b_acc)


_zeros_factories: dict = {}


def _zeros_sharded(shape, mesh: Optional[Mesh], spec: P) -> jax.Array:
    """Device-side zeros with the right sharding, via a cached compiled
    factory — a fresh ``jax.jit(lambda)`` per call would re-trace (and
    re-compile) the allocation on every half-iteration."""
    key = (shape, mesh, spec if mesh is not None else None)
    fn = _zeros_factories.get(key)
    if fn is None:
        if mesh is None:
            fn = jax.jit(lambda: jnp.zeros(shape, jnp.float32))
        else:
            fn = jax.jit(lambda: jnp.zeros(shape, jnp.float32),
                         out_shardings=NamedSharding(mesh, spec))
        _zeros_factories[key] = fn
    return fn()


def _update_side_split(fixed: jax.Array, sh: dict, params: "ALSParams",
                       block_rows: int) -> jax.Array:
    """One half-iteration in split mode. Accumulators live row-sharded
    like the factors; virtual-row blocks bound the [B, L, r] gather temp
    exactly as the pad path does."""
    implicit = params.implicit_prefs
    bf16 = params.matmul_dtype == "bfloat16"
    G = _fixed_gramian(fixed, sh["mesh"], params.gram_mode, bf16) \
        if implicit else None
    gsrc = fixed.astype(jnp.bfloat16) \
        if params.gather_dtype == "bfloat16" else fixed
    d, n_vper, L = sh["idx"].shape
    n_pad = sh["real_cnt"].shape[0]
    r = fixed.shape[-1]
    A_acc = _zeros_sharded((n_pad, r, r), sh["mesh"],
                           rows_spec(sh["mesh"]))
    b_acc = _zeros_sharded((n_pad, r), sh["mesh"], rows_spec(sh["mesh"]))
    for s in range(0, n_vper, block_rows):
        e = min(s + block_rows, n_vper)
        A_acc, b_acc = _partials_block(
            gsrc, sh["idx"][:, s:e], sh["val"][:, s:e],
            sh["cnt"][:, s:e], sh["rid"][:, s:e], A_acc, b_acc,
            params.alpha, implicit, bf16=bf16,
            gram=params.gram_mode, mesh=sh["mesh"])
    if G is None:
        G = jnp.zeros((r, r), jnp.float32)  # static arg shape filler
    return _solve_accumulated(A_acc, b_acc, G, sh["real_cnt"], params.reg,
                              implicit, params.scale_reg_by_count)


def _bucket_half_impl(fixed: jax.Array, out0: jax.Array, buckets,
                      reg, alpha, implicit: bool, scale_reg: bool,
                      bf16: bool, block_rows_opt,
                      gram: str = "auto",
                      gather_bf16: bool = False,
                      mesh: Optional[Mesh] = None) -> jax.Array:
    """Trace-level body of a bucketed half-iteration (jit-wrapped by
    :func:`_bucket_half_step` and inlined whole-training by
    :func:`_train_bucket_fused`)."""
    r = fixed.shape[-1]
    G = _fixed_gramian(fixed, mesh, gram, bf16) if implicit else None
    # the bf16 shadow (ALSParams.gather_dtype): gram/rhs/solve stay f32.
    # The barrier shares ONE materialized shadow across every bucket's
    # gather instead of letting XLA re-fuse the cast per bucket
    # (measured ≈ neutral on the 20M bench but keeps the shadow a
    # single buffer)
    gsrc = jax.lax.optimization_barrier(
        fixed.astype(jnp.bfloat16)) if gather_bf16 else fixed
    out = out0
    for b in buckets:
        d, n_per, L = b["idx"].shape
        block = block_rows_opt or _auto_block_rows(n_per, L, r)
        parts = []
        for s in range(0, n_per, block):
            e = min(s + block, n_per)
            parts.append(_update_block(
                gsrc, G, b["idx"][:, s:e], b["val"][:, s:e],
                b["cnt"][:, s:e], reg, alpha, implicit, scale_reg,
                bf16=bf16, gram=gram, mesh=mesh))
        new = parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                               axis=1)
        # each real row lives in exactly one bucket → unique indices (the
        # fast scatter regime; duplicate-index scatter-add serializes on
        # TPU); padding rows carry an out-of-range sentinel and drop
        out = out.at[b["rid"]].set(new.reshape(d * n_per, r),
                                   mode="drop", unique_indices=True)
    return out


@functools.partial(jax.jit,
                   static_argnames=("implicit", "scale_reg", "bf16",
                                    "block_rows_opt", "gram",
                                    "gather_bf16", "mesh"),
                   donate_argnums=(1,))
def _bucket_half_step(fixed: jax.Array, out0: jax.Array, buckets,
                      reg, alpha, *, implicit: bool, scale_reg: bool,
                      bf16: bool, block_rows_opt,
                      gram: str = "auto",
                      gather_bf16: bool = False,
                      mesh: Optional[Mesh] = None) -> jax.Array:
    """One ENTIRE bucketed half-iteration as a single compiled program —
    Gramian, every bucket's normal-equation blocks, solves, and the
    unique-index scatters all fuse into one dispatch. Separate per-bucket
    dispatches (plus their unjitted slice ops) cost ~25× the actual
    compute in per-op overhead through a remote-device tunnel.

    ``reg``/``alpha`` stay traced so hyperparameter sweeps reuse the
    compilation; the bucket STRUCTURE (shapes) is the cache key.
    """
    return _bucket_half_impl(fixed, out0, buckets, reg, alpha, implicit,
                             scale_reg, bf16, block_rows_opt, gram,
                             gather_bf16, mesh)


def _update_side_bucket(fixed: jax.Array, bk: dict, params: "ALSParams"
                        ) -> jax.Array:
    """One half-iteration over a bucketed layout: per bucket, the same
    dense normal-equation update as the pad path (bucket counts ARE the
    true row totals — rows are never split). Contraction depth per
    bucket = its L, so every einsum feeds the MXU a deep K."""
    r = fixed.shape[-1]
    out0 = _zeros_sharded((bk["n_rows_padded"], r), bk["mesh"],
                          rows_spec(bk["mesh"]))
    return _bucket_half_step(
        fixed, out0, tuple(bk["buckets"]), params.reg, params.alpha,
        implicit=params.implicit_prefs,
        scale_reg=params.scale_reg_by_count,
        bf16=(params.matmul_dtype == "bfloat16"),
        block_rows_opt=params.block_rows, gram=params.gram_mode,
        gather_bf16=(params.gather_dtype == "bfloat16"),
        mesh=bk["mesh"])


def _pad_half_impl(fixed: jax.Array, lay: dict, block: int, reg, alpha,
                   implicit: bool, scale_reg: bool, bf16: bool,
                   gram: str, gather_bf16: bool = False,
                   mesh: Optional[Mesh] = None) -> jax.Array:
    """One pad-layout half-iteration (trace-level body): Gramian, row
    blocks through :func:`_update_block`, flat reshape. SHARED by the
    per-step path (:func:`_update_side`) and the fused whole-run
    trainer — the two must never diverge."""
    G = _fixed_gramian(fixed, mesh, gram, bf16) if implicit else None
    gsrc = jax.lax.optimization_barrier(
        fixed.astype(jnp.bfloat16)) if gather_bf16 else fixed
    d, n_per, L = lay["idx"].shape
    parts = []
    for st in range(0, n_per, block):
        e = min(st + block, n_per)
        parts.append(_update_block(
            gsrc, G, lay["idx"][:, st:e], lay["val"][:, st:e],
            lay["cnt"][:, st:e], reg, alpha, implicit, scale_reg,
            bf16=bf16, gram=gram, mesh=mesh))
    out = parts[0] if len(parts) == 1 \
        else jnp.concatenate(parts, axis=1)
    return out.reshape(d * n_per, out.shape[-1])


@functools.partial(jax.jit,
                   static_argnames=("implicit", "scale_reg", "bf16",
                                    "gram", "kind_u", "kind_i",
                                    "block_u", "block_i",
                                    "block_rows_opt", "nu", "ni",
                                    "shard_u", "shard_i",
                                    "gather_bf16"))
def _train_fused(U: jax.Array, V: jax.Array, lay_u, lay_i, reg, alpha,
                 iters, *, implicit: bool, scale_reg: bool, bf16: bool,
                 gram: str, kind_u: str, kind_i: str, block_u: int,
                 block_i: int, block_rows_opt, nu: int, ni: int,
                 shard_u, shard_i,
                 gather_bf16: bool = False) -> Tuple[jax.Array, jax.Array]:
    """The WHOLE training run as ONE compiled program (no
    checkpointing): through a remote-device tunnel, per-dispatch latency
    rivals a full half-iteration of compute, so 2·iters·blocks
    dispatches cost more than the math. Each side's half-step is chosen
    STATICALLY by its layout kind ("pad" or "bucket" — mixed sides are a
    normal history_mode='auto' outcome on skewed data), both realized by
    the same impls the per-step path uses. ``iters`` stays traced (a
    sweep over iteration counts shares one compilation); ``shard_*`` are
    NamedShardings (hashable, static) constraining each half-step's
    output on a mesh."""

    def half(fixed, kind, lay, block, n_total, shard):
        mesh = None if shard is None else shard.mesh
        if kind == "bucket":
            out0 = jnp.zeros((n_total, fixed.shape[-1]), fixed.dtype)
            if shard is not None:
                out0 = jax.lax.with_sharding_constraint(out0, shard)
            return _bucket_half_impl(fixed, out0, lay, reg, alpha,
                                     implicit, scale_reg, bf16,
                                     block_rows_opt, gram, gather_bf16,
                                     mesh)
        out = _pad_half_impl(fixed, lay, block, reg, alpha, implicit,
                             scale_reg, bf16, gram, gather_bf16, mesh)
        if shard is not None:
            out = jax.lax.with_sharding_constraint(out, shard)
        return out

    def body(_, UV):
        U, V = UV
        U = half(V, kind_u, lay_u, block_u, nu, shard_u)
        V = half(U, kind_i, lay_i, block_i, ni, shard_i)
        return U, V

    # fori_loop, not Python unrolling: program size must not scale with
    # num_iterations (a 200-iteration run would otherwise inline 400
    # half-steps into one XLA program)
    return jax.lax.fori_loop(0, iters, body, (U, V))


def _update_side(fixed: jax.Array, indices: jax.Array, values: jax.Array,
                 counts: jax.Array, params: "ALSParams",
                 block_rows: int,
                 mesh: Optional[Mesh] = None) -> jax.Array:
    """One half-iteration, row-blocked to bound the [B, L, r] gather's
    memory (ALX-style batched updates); the per-step twin of the fused
    trainer — both route through :func:`_pad_half_impl`."""
    return _pad_half_impl(
        fixed, {"idx": indices, "val": values, "cnt": counts},
        block_rows, params.reg, params.alpha, params.implicit_prefs,
        params.scale_reg_by_count,
        bf16=(params.matmul_dtype == "bfloat16"),
        gram=params.gram_mode,
        gather_bf16=(params.gather_dtype == "bfloat16"),
        mesh=mesh)


@functools.partial(jax.jit, static_argnames=("n", "n_padded", "rank"))
def _init_factors(key: jax.Array, n: int, n_padded: int, rank: int
                  ) -> jax.Array:
    """MLlib-style init: N(0,1)/sqrt(rank) for the real rows, zeros for
    padding — the draw depends only on ``n`` so results are identical for
    any mesh size, and zero padding rows stay exactly zero through updates
    (their b is 0) without polluting the implicit Gramian. Jitted: the
    unjitted op-by-op version cost seconds per call through a remote
    device tunnel."""
    f = (jax.random.normal(key, (n, rank), dtype=jnp.float32)
         / jnp.sqrt(float(rank)))
    if n_padded > n:
        f = jnp.vstack([f, jnp.zeros((n_padded - n, rank), jnp.float32)])
    return f


def _shard(x, mesh: Optional[Mesh], spec: P):
    if mesh is None:
        # device_put, NOT jnp.asarray: asarray routes through the eager
        # op machinery — one blocking dispatch round trip per array,
        # measured ~80ms each through the tunnel (7.5s for a bucketed
        # layout's ~90 arrays); device_put transfers asynchronously
        # (same dtype canonicalization)
        return jax.device_put(x)
    return jax.device_put(x, NamedSharding(mesh, spec))


_init_sharded_cache: dict = {}


def _init_factors_sharded(key: jax.Array, n: int, n_padded: int,
                          rank: int, mesh: Optional[Mesh]) -> jax.Array:
    """Factor init with the output DIRECTLY computed into the row
    sharding (jit ``out_shardings``) — under multi-controller JAX a
    plain jit output lands on the local default device and a host-side
    ``device_put`` to a cross-process sharding is not generally legal,
    so the sharding must come out of the compiled program itself."""
    if mesh is None:
        return _init_factors(key, n=n, n_padded=n_padded, rank=rank)
    ck = (tuple(mesh.devices.flat), mesh.axis_names)  # jit's static-arg
    fn = _init_sharded_cache.get(ck)                  # cache handles shapes
    if fn is None:
        fn = jax.jit(_init_factors.__wrapped__,
                     static_argnames=("n", "n_padded", "rank"),
                     out_shardings=NamedSharding(mesh, rows_spec(mesh)))
        _init_sharded_cache[ck] = fn
    return fn(key, n=n, n_padded=n_padded, rank=rank)


def _auto_block_rows(n_per: int, L: int, rank: int) -> int:
    """Per-device rows per update block, targeting ~1GB for the [B, L, r]
    f32 gather temp. Fewer, bigger blocks matter more than temp memory:
    each block is a separate dispatch, and measured on a v5e chip the
    half-step went 414M→2.4B ratings/s/iter moving 128MB→1GB (68→9
    dispatches); HBM comfortably holds the temp beside factors+histories."""
    budget = 1024 * 1024 * 1024
    b = max(64, budget // max(1, L * rank * 4))
    return min(n_per, b)


def _blocked(h: PaddedHistories, n_dev: int, mesh: Optional[Mesh]) -> dict:
    """Host → device: reshape [N, …] histories to the [n_dev, N/n_dev, …]
    blocked layout and shard the leading axis over all mesh devices, so
    every row block spans every device."""
    n_per = h.n_rows // n_dev
    spec = rows_spec(mesh)
    return {
        "idx": _shard(h.indices.reshape(n_dev, n_per, h.max_len), mesh, spec),
        "val": _shard(h.values.reshape(n_dev, n_per, h.max_len), mesh, spec),
        "cnt": _shard(h.counts.reshape(n_dev, n_per), mesh, spec),
    }


def _blocked_split(sh: SplitHistories, n_dev: int,
                   mesh: Optional[Mesh]) -> dict:
    """Split-mode device layout: virtual-row arrays blocked like
    :func:`_blocked`; real-row accumulator metadata stays flat+sharded."""
    n_vper = sh.n_virtual // n_dev
    spec = rows_spec(mesh)
    return {
        "mode": "split",
        "mesh": mesh,
        "idx": _shard(sh.indices.reshape(n_dev, n_vper, sh.max_len),
                      mesh, spec),
        "val": _shard(sh.values.reshape(n_dev, n_vper, sh.max_len),
                      mesh, spec),
        "cnt": _shard(sh.counts.reshape(n_dev, n_vper), mesh, spec),
        "rid": _shard(sh.row_ids.reshape(n_dev, n_vper), mesh, spec),
        "real_cnt": _shard(sh.real_counts, mesh, spec),
    }


def _blocked_bucket(bh: BucketedHistories, n_dev: int,
                    mesh: Optional[Mesh]) -> dict:
    """Bucketed device layout. Buckets with at least one row per device
    shard the ROW axis (like the pad path); skinnier buckets (the few
    mega-popular rows) shard the L axis instead — their normal-equation
    einsum contracts over L, which GSPMD turns into per-device partial
    Gramians + an all-reduce, so even a single 10M-entry row spreads
    across the mesh."""
    spec_rows = rows_spec(mesh)
    all_axes = None if mesh is None else tuple(mesh.axis_names)
    buckets = []
    for b in bh.buckets:
        n_bk, L = b.indices.shape
        # count REAL rows (padding carries the sentinel): a bucket with
        # fewer real rows than devices would leave most of the mesh
        # holding padding under row sharding
        n_real = int((np.asarray(b.row_ids) < bh.n_rows_padded).sum())
        if n_real >= n_dev or L % n_dev != 0:
            shape = (n_dev, n_bk // n_dev, L)
            spec = spec_rows
            cnt_spec = spec_rows
        else:  # row-axis thinner than the mesh: shard the history axis
            shape = (1, n_bk, L)
            spec = P(None, None, all_axes)
            cnt_spec = P(None, None)
        buckets.append({
            "idx": _shard(b.indices.reshape(shape), mesh, spec),
            "val": _shard(b.values.reshape(shape), mesh, spec),
            "cnt": _shard(b.counts.reshape(shape[:2]), mesh, cnt_spec),
            "rid": _shard(b.row_ids, mesh,
                          spec_rows if b.row_ids.shape[0]
                          % n_dev == 0 else P(None)),
        })
    return {
        "mode": "bucket",
        "mesh": mesh,
        "buckets": buckets,
        "n_rows_padded": bh.n_rows_padded,
    }


def auto_split_len(counts: np.ndarray) -> int:
    """Pick the split-mode padded length: the power-of-two L in [32, 8192]
    minimizing total padded entries Σ ⌈c/L⌉·L (padding waste vs
    virtual-row count both fall out of this objective; ties → larger L =
    fewer scatter rows)."""
    best_L, best_total = 32, None
    c = counts[counts > 0]
    if c.size == 0:
        return 32
    for p in range(5, 14):  # 32 .. 8192
        L = 1 << p
        total = int((-(-c // L) * L).sum())
        if best_total is None or total <= best_total:
            best_L, best_total = L, total
    return best_L


def _pack(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
          n_rows: int, params: "ALSParams", n_dev: int):
    """History packing for one side; the sort/scatter runs on device
    (host numpy packing costs ~10s at MovieLens-20M scale — hard part 2
    of SURVEY §7 is exactly this host round-trip, so it's eliminated).
    Layout choice (``history_mode``): pad when nothing would drop, split
    when skew would otherwise truncate entries (drop-free, MLlib parity).
    """
    from ..ops.ragged import (
        AUTO_CAP_ENTRIES,
        pack_histories_bucketed_device,
        pack_histories_device,
        pack_histories_split_device,
        resolve_max_len,
    )

    max_history = params.max_history
    mode = params.history_mode
    counts = None
    if mode == "auto":
        if max_history is not None:
            mode = "pad"  # an explicit cap keeps round-1 semantics
        else:
            counts = np.bincount(rows, minlength=n_rows)
            L_full = int(counts.max(initial=1))
            slots = n_rows * L_full
            # pad must fit the absolute cap AND not waste HBM: at skew,
            # rows padded to the longest history can blow memory by 30x+
            # (measured: a 5%-sample eval fold padded 0.5M entries into
            # 33M slots per side — RESOURCE_EXHAUSTED through the device
            # tunnel). The bucketed layout bounds waste at ~2x.
            dense_enough = slots <= max(4 * len(rows), 1_000_000)
            mode = "pad" if (slots <= AUTO_CAP_ENTRIES
                             and dense_enough) else "bucket"
    if mode == "bucket":
        return pack_histories_bucketed_device(
            rows, cols, vals, n_rows, pad_rows_to=n_dev,
            max_len=None if max_history is None else int(max_history),
            counts=counts)
    if mode == "split":
        import warnings

        warnings.warn(
            "history_mode='split' scatter-adds duplicate row indices, "
            "which TPUs serialize — measured ~5x slower than 'bucket' "
            "at MovieLens-20M scale (BASELINE.md). 'bucket' is the "
            "drop-free layout of choice; 'split' is kept for "
            "comparison runs.", UserWarning, stacklevel=3)
        if counts is None:
            counts = np.bincount(rows, minlength=n_rows)
        L = int(max_history) if max_history is not None \
            else auto_split_len(counts)
        return pack_histories_split_device(rows, cols, vals, n_rows,
                                           max(L, 1), pad_rows_to=n_dev)
    if max_history is not None:
        L = int(max_history)
    else:
        counts = np.bincount(rows, minlength=n_rows) if counts is None \
            else counts
        L = resolve_max_len(counts, n_rows, None)
    return pack_histories_device(rows, cols, vals, n_rows, max(L, 1),
                                 pad_rows_to=n_dev)


@dataclass
class PackedRatings:
    """Packed histories for both sides plus a cache of their blocked
    device layouts. ``train_als`` per-call work on a pre-packed problem is
    then just the compiled update dispatches — re-deriving the blocked
    reshape/shard layout every call costs seconds through a remote device
    tunnel, which dwarfed the 36ms of actual compute in sweeps.

    Duck-compatible with the former ``(user_h, item_h)`` tuple return of
    :func:`pack_ratings` (iteration and indexing)."""

    user_h: object
    item_h: object
    mesh: Optional[Mesh] = None
    #: real (unpadded) problem dims — lets ``train_als(None, packed=...)``
    #: run without the host holding any RatingsCOO (multi-host partial
    #: reads feed shards straight from storage)
    n_users: Optional[int] = None
    n_items: Optional[int] = None
    _blocked: dict = field(default_factory=dict, repr=False)
    _lock: object = field(default_factory=threading.Lock, repr=False)

    def __iter__(self):
        return iter((self.user_h, self.item_h))

    def __getitem__(self, i: int):
        return (self.user_h, self.item_h)[i]

    def blocked(self, side: str, n_dev: int, mesh: Optional[Mesh]) -> dict:
        key = (side, n_dev, None if mesh is None else tuple(mesh.devices.flat))
        # compute-once under the lock: parallel sweeps hit the same
        # layout from several threads, and re-deriving it means repeated
        # device transfers
        with self._lock:
            out = self._blocked.get(key)
            if out is None:
                h = self.user_h if side == "user" else self.item_h
                if isinstance(h, BucketedHistories):
                    out = _blocked_bucket(h, n_dev, mesh)
                elif isinstance(h, SplitHistories):
                    out = _blocked_split(h, n_dev, mesh)
                else:
                    out = _blocked(h, n_dev, mesh)
                self._blocked[key] = out
        return out


def pack_ratings(ratings: RatingsCOO, params: ALSParams,
                 mesh: Optional[Mesh] = None) -> PackedRatings:
    """Pre-pack both sides' histories for :func:`train_als`.

    Packing ships the COO to the device once; hyperparameter sweeps (and
    benchmarks) should pack once and pass ``packed=`` to every
    ``train_als`` call so retrains skip the transfer + sort. Under a
    multi-controller runtime this routes to
    :func:`pack_ratings_multihost` (per-process device feeding)."""
    enable_compilation_cache()
    if mesh is not None and jax.process_count() > 1:
        return pack_ratings_multihost(ratings, params, mesh)
    if hasattr(ratings, "to_coo"):  # a sharded source on one host
        ratings = ratings.to_coo()
    n_dev = 1 if mesh is None else mesh.devices.size
    user_h = _pack(ratings.users, ratings.items, ratings.ratings,
                   ratings.n_users, params, n_dev)
    item_h = _pack(ratings.items, ratings.users, ratings.ratings,
                   ratings.n_items, params, n_dev)
    return PackedRatings(user_h=user_h, item_h=item_h, mesh=mesh,
                         n_users=ratings.n_users, n_items=ratings.n_items)


#: id(ratings) → (weakref-to-ratings, per-ratings ComputeOnce). The pack
#: depends on params only through the layout knobs (history_mode,
#: max_history) and the mesh — NOT rank/reg/alpha/iterations — so an
#: eval sweep over algorithm hyperparameters re-uses one packing per
#: fold (VERDICT r1 task 7: sweeps re-paid the COO ship + sort every
#: retrain).
_pack_cache: dict = {}
_pack_cache_lock = threading.Lock()


def pack_ratings_cached(ratings: RatingsCOO, params: ALSParams,
                        mesh: Optional[Mesh] = None) -> PackedRatings:
    """Memoizing :func:`pack_ratings`: keyed by the identity of the
    ratings object and the packing-relevant params. Compute-once across
    threads (a parallel sweep's workers all miss together during the
    long transfer-and-sort window otherwise; failed packs retry);
    entries die with the ratings object (weakref callback), so folds
    don't pin device memory past their evaluation."""
    import weakref

    from ..utils.memo import ComputeOnce

    with _pack_cache_lock:
        ent = _pack_cache.get(id(ratings))
        if ent is None or ent[0]() is not ratings:
            rid = id(ratings)
            ref = weakref.ref(ratings,
                              lambda _, i=rid: _pack_cache.pop(i, None))
            ent = _pack_cache[rid] = (ref, ComputeOnce(retry_on_failure=True))
        memo = ent[1]
    key = (params.max_history, params.history_mode,
           None if mesh is None else tuple(mesh.devices.flat))
    return memo.get(key, lambda: pack_ratings(ratings, params, mesh))


def pack_ratings_multihost(ratings, params: ALSParams,
                           mesh: Mesh, force: bool = False
                           ) -> PackedRatings:
    """Multi-controller packing (``jax.process_count() > 1``): every
    process packs ONLY the history rows its local devices own and the
    global blocked arrays are assembled from per-process shards
    (``jax.make_array_from_process_local_data`` — the Spark-executor
    feeding role, SURVEY §2.3). Single-process falls through to
    :func:`pack_ratings`.

    v2 contract (partial reads): ``ratings`` may be a *sharded source*
    (``read_rows``/``row_counts`` — e.g.
    :class:`~predictionio_tpu.models.data.ColumnarRatingsSource` over a
    shared-filesystem columnar sidecar), in which case each process
    MATERIALIZES only the rating triples of its own row range — the
    ``JDBCPEvents.scala:49-89`` partitioned-read role. A plain
    :class:`RatingsCOO` (every host already holding the global COO)
    still works.

    Layouts: "auto" resolves per side like the single-host pack — pad
    when nothing would drop, otherwise the DROP-FREE bucketed layout,
    whose per-bucket rows are padded to the device count and sharded so
    each process packs only its own bucket rows ("split" maps to bucket
    here: its duplicate-index scatter has no multihost layout).
    """
    import jax

    from ..ops.ragged import pack_histories, resolve_max_len

    if jax.process_count() == 1 and not force:
        return pack_ratings(ratings, params, mesh)

    n_dev = mesh.devices.size
    flat = list(mesh.devices.flat)
    pid = jax.process_index()
    mine = [i for i, d in enumerate(flat) if d.process_index == pid]
    if not mine:
        raise ValueError(f"process {pid} owns no devices in the mesh; "
                         "build the mesh over every process's devices")
    if mine != list(range(mine[0], mine[-1] + 1)):
        raise ValueError("pack_ratings_multihost requires each process's "
                         "devices to be contiguous in mesh order")

    from ..ops.ragged import AUTO_CAP_ENTRIES

    is_source = hasattr(ratings, "read_rows")
    packed = PackedRatings(user_h=None, item_h=None, mesh=mesh,
                           n_users=ratings.n_users,
                           n_items=ratings.n_items)
    sides = {"user": ratings.n_users, "item": ratings.n_items}
    hs = {}
    for side, n_rows in sides.items():
        if is_source:
            counts = np.asarray(ratings.row_counts(side))
        else:
            rows_g = ratings.users if side == "user" else ratings.items
            counts = np.bincount(rows_g, minlength=n_rows)

        mode = params.history_mode
        bucket_cap = params.max_history and int(params.max_history)
        if mode == "split":
            # split's duplicate-index scatter has no multihost layout;
            # bucket covers its drop-free role. Split keeps EVERY entry
            # (its max_history is the virtual-row length, not a cap), so
            # the bucket stand-in must be uncapped too.
            mode = "bucket"
            bucket_cap = None
        elif mode == "auto":
            if params.max_history is not None:
                mode = "pad"
            else:
                L_full = int(counts.max(initial=1))
                mode = "pad" if n_rows * L_full <= AUTO_CAP_ENTRIES \
                    else "bucket"
        if mode == "bucket":
            # drop-free layout, sharded per process (each packs only
            # the bucket rows its devices own)
            if is_source:
                def rrm(m, _side=side):
                    return ratings.read_row_mask(_side, m)
            else:
                rows_g = ratings.users if side == "user" \
                    else ratings.items
                cols_g = ratings.items if side == "user" \
                    else ratings.users

                def rrm(m, _r=rows_g, _c=cols_g):
                    sel = m[_r]
                    return _r[sel], _c[sel], ratings.ratings[sel]
            layout, h = _pack_side_bucket_multihost(
                rrm, counts, n_rows, mesh, mine, bucket_cap)
            packed._blocked[(side, n_dev,
                             tuple(mesh.devices.flat))] = layout
            hs[side] = h
            continue

        L = resolve_max_len(counts, n_rows,
                            params.max_history and int(params.max_history))
        n_pad = -(-n_rows // n_dev) * n_dev
        n_per = n_pad // n_dev
        start, stop = mine[0] * n_per, (mine[-1] + 1) * n_per
        if is_source:
            rows_l, cols_l, vals_l = ratings.read_rows(
                side, start, min(stop, n_rows))
        else:
            rows_g = ratings.users if side == "user" else ratings.items
            cols_g = ratings.items if side == "user" else ratings.users
            sel = (rows_g >= start) & (rows_g < min(stop, n_rows))
            rows_l, cols_l, vals_l = rows_g[sel], cols_g[sel], \
                ratings.ratings[sel]
        local = pack_histories(rows_l - start, cols_l, vals_l,
                               n_rows=stop - start, max_len=L,
                               pad_rows_to=1)
        d_loc = len(mine)
        sharding = NamedSharding(mesh, rows_spec(mesh))

        def glob(arr, tail_shape):
            return jax.make_array_from_process_local_data(
                sharding, arr.reshape((d_loc,) + tail_shape),
                (n_dev,) + tail_shape)

        blocked = {
            "idx": glob(local.indices, (n_per, L)),
            "val": glob(local.values, (n_per, L)),
            "cnt": glob(local.counts, (n_per,)),
        }
        key = (side, n_dev, tuple(mesh.devices.flat))
        packed._blocked[key] = blocked
        # n_rows/max_len drive factor sizing, _auto_block_rows and the
        # flops model; the host-side padded matrices never exist globally
        hs[side] = _LayoutOnlyHistories(n_rows=n_pad, max_len=L)
    packed.user_h = hs["user"]
    packed.item_h = hs["item"]
    return packed


@dataclass(frozen=True)
class _LayoutOnlyHistories:
    """Shape metadata standing in for a PaddedHistories whose blocked
    device arrays were assembled directly from per-process shards (the
    host-side padded matrices never exist globally)."""

    n_rows: int
    max_len: int


@dataclass(frozen=True)
class _LayoutOnlyBucket:
    length: int
    n_rows: int  # padded member rows


@dataclass(frozen=True)
class _LayoutOnlyBucketed:
    """Shape metadata standing in for a BucketedHistories assembled from
    per-process shards (duck-typed: padded_entries/n_rows_padded drive
    the FLOP model and factor sizing)."""

    buckets: tuple  # of _LayoutOnlyBucket
    n_rows: int
    n_rows_padded: int

    @property
    def padded_entries(self) -> int:
        return sum(b.n_rows * b.length for b in self.buckets)

    @property
    def max_len(self) -> int:
        return max((b.length for b in self.buckets), default=1)


def _pack_side_bucket_multihost(read_row_mask, counts: np.ndarray,
                                n_rows: int, mesh: Mesh, mine: list,
                                max_len: Optional[int]):
    """One side of the DROP-FREE multihost packing: every process
    derives the same global bucket plan from the same ``counts``, packs
    ONLY the bucket rows its devices own (an arbitrary row set — bucket
    membership is by history length), and returns per-bucket local
    arrays ready for ``jax.make_array_from_process_local_data``.

    Unlike the single-host layout, skinny buckets also shard by rows
    (L-axis sharding would split single rows' entries across processes
    by position); their padding rows solve to zero and drop."""
    import jax

    from ..ops.ragged import bucket_layout
    from ..ops.ragged import _pack_flat_on_device as pack_flat

    n_dev = mesh.devices.size
    d_loc = len(mine)
    if max_len is not None:
        counts = np.minimum(counts, int(max_len))
    plan, _, _ = bucket_layout(counts, min_len=8, pad_rows_to=n_dev,
                               max_len=None)
    n_rows_pad = max(-(-n_rows // n_dev) * n_dev, n_dev)

    # local destination map: global row -> offset in THIS process's flat
    # buffer (only rows this process owns; others stay -1)
    local_base = np.full(n_rows, -1, dtype=np.int64)
    owned = np.zeros(n_rows, dtype=bool)
    spans = []  # (L, rows_local, n_loc_slots, off_loc, rid_local)
    off_loc = 0
    for L, rows_k, n_bk_pad, _ in plan:
        npb = n_bk_pad // n_dev
        lo, hi = mine[0] * npb, (mine[-1] + 1) * npb
        rows_local = rows_k[lo:min(hi, len(rows_k))]
        n_loc = d_loc * npb
        rid_global = (n_rows_pad
                      + np.arange(n_bk_pad, dtype=np.int64)
                      - len(rows_k)).astype(np.int32)
        rid_global[:len(rows_k)] = rows_k
        local_base[rows_local] = off_loc + np.arange(
            len(rows_local), dtype=np.int64) * int(L)
        owned[rows_local] = True
        spans.append((int(L), rows_local, n_loc, off_loc,
                      rid_global[lo:hi]))
        off_loc += n_loc * int(L)
    S_loc = off_loc
    if S_loc >= 2 ** 31:  # pragma: no cover — >1B padded slots/process
        raise ValueError(
            f"bucketed multihost layout needs {S_loc} local slots "
            f"(> int32); use more processes or cap max_history")

    rows_l, cols_l, vals_l = read_row_mask(owned)
    flat_idx, flat_val = pack_flat(
        jnp.asarray(rows_l, dtype=jnp.int32),
        jnp.asarray(cols_l, dtype=jnp.int32),
        jnp.asarray(vals_l, dtype=jnp.float32),
        jnp.asarray(local_base, dtype=jnp.int32),
        jnp.asarray(counts, dtype=jnp.int32),
        n_rows=n_rows, S=max(S_loc, 1))
    flat_idx = np.asarray(flat_idx)
    flat_val = np.asarray(flat_val)

    sharding_rows = NamedSharding(mesh, rows_spec(mesh))
    sharding_cnt = NamedSharding(mesh, rows_spec(mesh))
    buckets = []
    layout_buckets = []
    for L, rows_local, n_loc, off, rid_local in spans:
        npb = n_loc // d_loc
        n_bk_pad = npb * n_dev
        idx_loc = flat_idx[off:off + n_loc * L].reshape(d_loc, npb, L)
        val_loc = flat_val[off:off + n_loc * L].reshape(d_loc, npb, L)
        cnt_loc = np.zeros(n_loc, dtype=np.int32)
        cnt_loc[:len(rows_local)] = counts[rows_local]
        buckets.append({
            "idx": jax.make_array_from_process_local_data(
                sharding_rows, idx_loc, (n_dev, npb, L)),
            "val": jax.make_array_from_process_local_data(
                sharding_rows, val_loc, (n_dev, npb, L)),
            "cnt": jax.make_array_from_process_local_data(
                sharding_cnt, cnt_loc.reshape(d_loc, npb),
                (n_dev, npb)),
            "rid": jax.make_array_from_process_local_data(
                sharding_rows, np.ascontiguousarray(rid_local),
                (n_bk_pad,)),
        })
        layout_buckets.append(_LayoutOnlyBucket(length=L,
                                                n_rows=n_bk_pad))
    layout = {"mode": "bucket", "mesh": mesh, "buckets": buckets,
              "n_rows_padded": n_rows_pad}
    h = _LayoutOnlyBucketed(buckets=tuple(layout_buckets),
                            n_rows=n_rows, n_rows_padded=n_rows_pad)
    return layout, h


def train_als(ratings: RatingsCOO, params: ALSParams,
              mesh: Optional[Mesh] = None,
              packed: Optional[Tuple[PaddedHistories, PaddedHistories]]
              = None,
              checkpoint_dir: Optional[str] = None,
              checkpoint_every: int = 0
              ) -> Tuple[jax.Array, jax.Array]:
    """Run ALS; returns (user_factors, item_factors) with padded rows.

    Under a mesh, factor matrices and histories are row-sharded over all
    devices; each half-iteration runs as row blocks whose collectives
    (Gramian all-reduce, cross-shard factor gathers) XLA derives from the
    shardings. ``packed`` (from :func:`pack_ratings` with the SAME params
    + mesh) skips history packing.

    With ``checkpoint_dir``, factors are checkpointed every
    ``checkpoint_every`` iterations and a restarted call resumes from
    the latest saved iteration (step-level resume, SURVEY §5 — the
    reference restarts training from scratch after any failure).
    """
    enable_compilation_cache()
    if ratings is None:
        # multi-host partial reads: the host never holds a global COO;
        # the packed layout carries the problem dims instead
        if not (isinstance(packed, PackedRatings)
                and packed.n_users and packed.n_items):
            raise ValueError(
                "train_als(ratings=None) needs packed=PackedRatings with "
                "n_users/n_items (from pack_ratings/_multihost)")
        if checkpoint_dir:
            raise ValueError(
                "checkpointing fingerprints the ratings content; pass "
                "the ratings (or use checkpoint_dir=None) ")
        n_users_real, n_items_real = packed.n_users, packed.n_items
    elif hasattr(ratings, "read_rows"):  # a sharded source
        if ratings.n_users == 0 or ratings.n_items == 0:
            raise ValueError("ALS requires a non-empty ratings matrix "
                             "(0 users/items in the source)")
        if checkpoint_dir:
            raise ValueError(
                "checkpointing fingerprints the ratings content; pass a "
                "RatingsCOO (source.to_coo()) when using checkpoint_dir")
        n_users_real, n_items_real = ratings.n_users, ratings.n_items
    else:
        if len(ratings.users) == 0 or ratings.n_users == 0 \
                or ratings.n_items == 0:
            raise ValueError("ALS requires a non-empty ratings matrix "
                             "(0 entries/users/items given)")
        n_users_real, n_items_real = ratings.n_users, ratings.n_items
    n_dev = 1 if mesh is None else mesh.devices.size
    if packed is None:
        packed = pack_ratings(ratings, params, mesh)
    elif not isinstance(packed, PackedRatings):
        packed = PackedRatings(user_h=packed[0], item_h=packed[1], mesh=mesh)
    user_h, item_h = packed.user_h, packed.item_h

    u_split = isinstance(user_h, SplitHistories)
    i_split = isinstance(item_h, SplitHistories)
    # duck-typed: multihost bucket layouts stand in via
    # _LayoutOnlyBucketed, which also carries n_rows_padded
    u_rows_pad = getattr(user_h, "n_rows_padded", None) or user_h.n_rows
    i_rows_pad = getattr(item_h, "n_rows_padded", None) or item_h.n_rows

    ku, ki = jax.random.split(jax.random.key(params.seed))
    U = _init_factors_sharded(ku, n_users_real, u_rows_pad,
                              params.rank, mesh)
    V = _init_factors_sharded(ki, n_items_real, i_rows_pad,
                              params.rank, mesh)
    uh = packed.blocked("user", n_dev, mesh)
    ih = packed.blocked("item", n_dev, mesh)

    ckpt = None
    start = 0
    fingerprint = ""
    if checkpoint_dir:
        import hashlib
        import json as _json

        from ..workflow.checkpoint import (
            DistributedCheckpointer,
            make_checkpointer,
        )

        if checkpoint_every <= 0:
            checkpoint_every = 1  # a checkpoint dir implies checkpointing
        # refuse to resume from a different problem/params: fingerprint
        # everything that determines the factor trajectory
        # cheap content digest so a *different* dataset with identical
        # shape cannot silently resume from foreign factors: sample the
        # first/last 1024 COO triples (native dtype, no copies) plus
        # whole-array sums
        k = 1024
        content = hashlib.sha256()
        for arr in (np.asarray(ratings.users), np.asarray(ratings.items),
                    np.asarray(ratings.ratings)):
            content.update(np.ascontiguousarray(arr[:k]).tobytes())
            content.update(np.ascontiguousarray(arr[-k:]).tobytes())
            content.update(np.float64(arr.sum(dtype=np.float64)).tobytes())
        legacy_base = [
            params.rank, params.reg, params.alpha, params.implicit_prefs,
            params.seed, params.scale_reg_by_count, params.matmul_dtype,
            params.max_history,  # affects history truncation → trajectory
            ratings.n_users, ratings.n_items, len(ratings.users),
        ]
        base = legacy_base + [params.history_mode]
        if params.gather_dtype != "float32":
            # default-f32 fingerprints stay byte-identical to round-3
            # checkpoints; a bf16-shadow run has a different trajectory
            base = base + [params.gather_dtype]
        fingerprint = hashlib.sha256(_json.dumps(
            base + [content.hexdigest()]).encode()).hexdigest()[:16]
        # pre-content-digest dirs (round-1 scheme, no history_mode field)
        # stay resumable — but ONLY when this run resolved to round-1 pad
        # semantics on both sides: resuming a pad-trained checkpoint under
        # the new drop-free split layout would silently continue a
        # different objective
        accepted = (fingerprint,)
        if isinstance(user_h, PaddedHistories) \
                and isinstance(item_h, PaddedHistories):
            accepted += (hashlib.sha256(
                _json.dumps(legacy_base).encode()).hexdigest()[:16],)
        # multi-process runs get the preemption-safe distributed
        # container (per-process shard files + rendezvous commit,
        # ISSUE 11): every host writes only its local factor rows and
        # a kill -9 at any instant costs at most the step in flight
        ckpt = make_checkpointer(checkpoint_dir)
        meta = ckpt.get_metadata()
        if meta is not None \
                and meta.get("fingerprint") not in accepted:
            raise ValueError(
                f"checkpoint dir {checkpoint_dir} belongs to a different "
                f"ALS run (params/dataset/history-layout mismatch); use "
                f"a fresh dir")
        ckpt.set_metadata({"fingerprint": fingerprint})
        # resume from the newest RESTORABLE step within this run's
        # iteration budget — a torn step (crash mid-save) is skipped
        # and the walk falls back to the previous committed one
        start, state = ckpt.restore_latest(
            like={"U": U, "V": V}, max_step=params.num_iterations)
        if state is not None:
            if isinstance(ckpt, DistributedCheckpointer):
                # restore already reassembled + placed the local shards
                U, V = state["U"], state["V"]
            else:
                U = _shard(state["U"], mesh, rows_spec(mesh))
                V = _shard(state["V"], mesh, rows_spec(mesh))

    def _kind(h) -> str:
        if isinstance(h, (BucketedHistories, _LayoutOnlyBucketed)):
            return "bucket"
        if isinstance(h, (PaddedHistories, _LayoutOnlyHistories)):
            return "pad"
        return "split"

    kind_u, kind_i = _kind(user_h), _kind(item_h)
    if ckpt is None and "split" not in (kind_u, kind_i) \
            and start < params.num_iterations:
        # checkpoint-free runs compile the WHOLE training loop into one
        # dispatch, whatever mix of pad/bucket layouts auto resolved to
        shard = None if mesh is None \
            else NamedSharding(mesh, rows_spec(mesh))

        def _fused_args(kind, h, lay):
            if kind == "bucket":
                return tuple(lay["buckets"]), 0
            return lay, params.block_rows or _auto_block_rows(
                h.n_rows // n_dev, h.max_len, params.rank)

        lay_u, block_u = _fused_args(kind_u, user_h, uh)
        lay_i, block_i = _fused_args(kind_i, item_h, ih)
        return _train_fused(
            U, V, lay_u, lay_i, params.reg, params.alpha,
            params.num_iterations - start,
            implicit=params.implicit_prefs,
            scale_reg=params.scale_reg_by_count,
            bf16=(params.matmul_dtype == "bfloat16"),
            gram=params.gram_mode, kind_u=kind_u, kind_i=kind_i,
            block_u=block_u, block_i=block_i,
            block_rows_opt=params.block_rows,
            nu=u_rows_pad, ni=i_rows_pad,
            shard_u=shard, shard_i=shard,
            gather_bf16=(params.gather_dtype == "bfloat16"))

    def _stepper(h, layout):
        if isinstance(h, (BucketedHistories, _LayoutOnlyBucketed)):
            return lambda fixed: _update_side_bucket(fixed, layout, params)
        n_r = h.n_virtual if isinstance(h, SplitHistories) else h.n_rows
        blk = params.block_rows or _auto_block_rows(
            n_r // n_dev, h.max_len, params.rank)
        if isinstance(h, SplitHistories):
            return lambda fixed: _update_side_split(fixed, layout, params,
                                                    blk)
        return lambda fixed: _update_side(
            fixed, layout["idx"], layout["val"], layout["cnt"], params,
            blk, mesh)

    step_u = _stepper(user_h, uh)
    step_i = _stepper(item_h, ih)

    try:
        for it in range(start, params.num_iterations):
            U = step_u(V)
            V = step_i(U)
            if ckpt is not None:
                ckpt.maybe_save(it + 1, {"U": U, "V": V},
                                every=checkpoint_every)
    finally:
        if ckpt is not None:
            ckpt.close()
    return U, V


def als_flops_per_iter(user_h, item_h, params: ALSParams) -> int:
    """Padded-work FLOP model for ONE full ALS iteration (both half-steps)
    under the given packed layout — the denominator-side of an honest MFU
    number: it counts the floating-point work the device is actually asked
    to do (including padding slots), not the nominal nnz·r² lower bound.

    Per half-step over ``padded`` = virtual-rows×L history slots and
    ``n_solve`` solve rows of rank r:
    A outer products 2·padded·r², b products 2·padded·r, fixed-side
    Gramian 2·rows_fixed·r² (implicit only), Cholesky r³/3 + two
    triangular solves 2r² per row."""
    r = params.rank

    def side(h, fixed_rows: int) -> int:
        if isinstance(h, (BucketedHistories, _LayoutOnlyBucketed)):
            padded = h.padded_entries
            n_solve = sum(b.n_rows for b in h.buckets)
        elif isinstance(h, SplitHistories):
            padded = h.n_virtual * h.max_len
            n_solve = h.n_rows_padded
        else:
            padded = h.n_rows * h.max_len
            n_solve = h.n_rows
        f = 2 * padded * r * r + 2 * padded * r
        if params.implicit_prefs:
            f += 2 * fixed_rows * r * r
        f += n_solve * (r ** 3 // 3 + 2 * r * r)
        return f

    def rows_of(h):
        # duck-typed: _LayoutOnlyBucketed carries n_rows_padded too
        return getattr(h, "n_rows_padded", None) or h.n_rows

    return side(user_h, rows_of(item_h)) + side(item_h, rows_of(user_h))


# -- row-quantized serving factor tables (ISSUE 13) --------------------------
#
# Tensor-Casting-style precision co-design (arXiv 2010.13100):
# recommendation factors tolerate low-precision STORAGE as long as the
# accumulation stays f32. Serving-side tables are therefore stored
# int8 (per-row absmax scales) or bf16 and dequantized on the fly —
# 4x (int8) / 2x (bf16) more users per HBM and the same factor less
# bandwidth per scored batch, with every dot product still
# accumulating in f32. Deploy-time only, like the mesh: a quantized
# table never enters the blob store.

#: the ServerConfig.serving_quant vocabulary
SERVING_QUANT_MODES = ("off", "bf16", "int8")

#: NDCG@10-vs-f32 floor the deploy-time parity probe enforces before a
#: quantized table may serve (:func:`quantize_serving_model` auto-off:
#: a model trained at a rank/scale where per-row int8 loses the
#: ranking falls back to f32 instead of silently degrading quality)
SERVING_QUANT_NDCG_FLOOR = 0.97


@jax.tree_util.register_dataclass
@dataclass
class QuantizedFactors:
    """A row-quantized serving factor table: ``data`` [n, r] int8 with
    per-row f32 absmax ``scale`` [n, 1], or bf16 with no scale. A
    pytree (so device placement, sharding and ``nbytes`` accounting
    reach the leaves); ``quant`` is static metadata. Serving paths
    dequantize after the wire — upcast + scale inside the compiled
    program (or the fused kernel's VMEM), never as a materialized f32
    copy of the table."""

    data: jax.Array = field(metadata=dict(static=False))
    scale: Optional[jax.Array] = field(default=None,
                                       metadata=dict(static=False))
    quant: str = field(default="int8", metadata=dict(static=True))

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def nbytes(self) -> int:
        nb = int(self.data.nbytes)
        if self.scale is not None:
            nb += int(self.scale.nbytes)
        return nb


def _table_leaves(t) -> Tuple[jax.Array, Optional[jax.Array]]:
    """(data, scale-or-None) of a factor table, quantized or plain."""
    if isinstance(t, QuantizedFactors):
        return t.data, t.scale
    return t, None


def table_quant(t) -> str:
    """The quant dtype of a factor table ("off" for plain f32)."""
    return t.quant if isinstance(t, QuantizedFactors) else "off"


def serving_quant_of(model) -> str:
    """The serving-quant realization of a bound model — the ``quant``
    label of the ``pio_serving_kernel`` info gauge."""
    return table_quant(getattr(model, "item_factors", model))


def _quantize_rows(rows: np.ndarray, quant: str
                   ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Host-side row quantization: per-row absmax scale → int8 in
    [-127, 127] (symmetric, so dequant is one multiply), or a bf16
    cast. Shared by :func:`quantize_serving_model` and the streaming
    hot-swap's re-quantization (:func:`apply_row_updates`)."""
    rows = np.asarray(rows, dtype=np.float32)
    if quant == "bf16":
        import ml_dtypes

        return rows.astype(ml_dtypes.bfloat16), None
    if quant != "int8":
        raise ValueError(f"quant must be 'bf16' or 'int8', got {quant!r}")
    amax = np.max(np.abs(rows), axis=-1, keepdims=True) \
        if rows.size else np.zeros((rows.shape[0], 1), np.float32)
    scale = np.maximum(amax, 1e-12).astype(np.float32) / 127.0
    data = np.clip(np.rint(rows / scale), -127, 127).astype(np.int8)
    return data, scale


_dequant_scaled = jax.jit(lambda d, s: d.astype(jnp.float32) * s)
_dequant_plain = jax.jit(lambda d: d.astype(jnp.float32))


def dequantize_table(t):
    """An f32 view of a factor table (identity for plain tables).
    Elementwise, so a row-sharded quantized table dequantizes into the
    same sharding. Used by the training-side consumers of a serving
    table (streaming fold-in solves) — the serving paths themselves
    dequantize inside their compiled programs instead."""
    if not isinstance(t, QuantizedFactors):
        return t
    if t.scale is None:
        return _dequant_plain(t.data)
    return _dequant_scaled(t.data, t.scale)


def table_host_f32(t) -> np.ndarray:
    """Host f32 copy of a factor table (plain or quantized, device or
    host resident) — the fold-in residual / parity-probe view."""
    if isinstance(t, QuantizedFactors):
        data = np.asarray(jax.device_get(t.data)).astype(np.float32)
        if t.scale is not None:
            data = data * np.asarray(jax.device_get(t.scale))
        return data
    if isinstance(t, np.ndarray):
        return np.asarray(t, dtype=np.float32)
    return np.asarray(jax.device_get(t)).astype(np.float32)


def _binary_ndcg(ranked, relevant, k: int) -> float:
    """Binary NDCG@k of one ranked id list against a relevant-id set
    (inlined rather than imported from controller.metric: models must
    not depend on the controller layer)."""
    dcg = sum(1.0 / np.log2(i + 2.0)
              for i, x in enumerate(ranked[:k]) if x in relevant)
    ideal = sum(1.0 / np.log2(i + 2.0)
                for i in range(min(k, len(relevant))))
    return float(dcg / ideal) if ideal else 0.0


def serving_quant_ndcg(U: np.ndarray, V: np.ndarray, qU, qV,
                       n_items: int, k: int = 10, sample: int = 32,
                       seed: int = 0) -> float:
    """Mean NDCG@k of the QUANTIZED ranking against the f32 ranking's
    top-k (f32 as ground truth) over a user sample — the deploy-time
    parity probe behind the auto-off fallback, and the same statistic
    the CI quality gate asserts on a fixture model."""
    n = min(sample, U.shape[0])
    if n == 0 or n_items == 0:
        return 1.0
    users = np.random.default_rng(seed).choice(U.shape[0], size=n,
                                               replace=False)
    kk = min(k, n_items)
    ids_f, _ = _host_topk(U[users], V, kk, n_items)
    ids_q, _ = _host_topk(table_host_f32(qU)[users],
                          table_host_f32(qV), kk, n_items)
    return float(np.mean([
        _binary_ndcg(list(a), set(b.tolist()), kk)
        for a, b in zip(ids_q, ids_f)]))


def quantize_serving_model(model: "ALSModel", quant: str, *,
                           parity_floor: float = SERVING_QUANT_NDCG_FLOOR,
                           parity_sample: int = 32, parity_k: int = 10,
                           seed: int = 0) -> "ALSModel":
    """A model whose serving factor tables are row-quantized to
    ``quant`` ("int8" | "bf16"; "off" returns the input) — the
    ``ServerConfig.serving_quant`` realization, applied at bind time
    BEFORE device placement so the host→HBM transfer already moves the
    small tables.

    Auto-off: before committing, a parity probe ranks ``parity_sample``
    users through both tables and requires NDCG@``parity_k`` ≥
    ``parity_floor`` against the f32 ranking; a model whose rank/scale
    cannot take the quantization keeps its f32 tables (logged), so
    ``--serving-quant`` can never silently degrade ranking. The CI
    quality gate (tests/test_serving_quant.py) asserts the same
    statistic on a fixture model."""
    import dataclasses

    if quant in (None, "", "off"):
        return model
    if quant not in ("bf16", "int8"):
        raise ValueError(
            f"serving quant must be one of {SERVING_QUANT_MODES}, "
            f"got {quant!r}")
    if isinstance(model.user_factors, QuantizedFactors):
        return model
    U = table_host_f32(model.user_factors)
    V = table_host_f32(model.item_factors)
    qU = QuantizedFactors(*_quantize_rows(U, quant), quant=quant)
    qV = QuantizedFactors(*_quantize_rows(V, quant), quant=quant)
    if parity_floor and parity_sample > 0:
        ndcg = serving_quant_ndcg(U, V, qU, qV, model.n_items,
                                  k=parity_k, sample=parity_sample,
                                  seed=seed)
        if ndcg < parity_floor:
            import logging

            logging.getLogger(__name__).warning(
                "serving_quant=%s parity probe failed (NDCG@%d %.4f "
                "< %.2f vs f32); keeping full-precision serving "
                "tables (auto-off)", quant, parity_k, ndcg,
                parity_floor)
            return model
    return dataclasses.replace(model, user_factors=qU, item_factors=qV)


# -- serving ----------------------------------------------------------------

#: process-wide serving top-k override (None = the autotune table);
#: set per deploy from ``ServerConfig.serving_topk`` — an explicit
#: "fused" on a CPU host is a debugging/test run and exercises the
#: interpret-mode kernel, mirroring ``gram_mode="fused"``
_serving_topk_override: Optional[str] = None


def set_serving_topk_mode(mode: Optional[str]) -> None:
    """Pin the batched-lane top-k realization ("einsum" | "fused");
    None/"auto" returns control to the support-gated autotune table
    (``ops/gram_autotune.best_topk_mode``)."""
    global _serving_topk_override
    if mode in (None, "", "auto"):
        _serving_topk_override = None
        return
    if mode not in ("einsum", "fused"):
        raise ValueError(
            f"serving topk mode must be 'auto', 'einsum' or 'fused', "
            f"got {mode!r}")
    _serving_topk_override = mode


def resolved_topk_mode(rank: int, quant: str = "off") -> str:
    """The concrete serving top-k realization ("einsum" | "fused") for
    the attached backend — the ``mode`` label of the
    ``pio_serving_kernel`` info gauge (docs/observability.md)."""
    if _serving_topk_override is not None:
        return _serving_topk_override
    from ..ops.gram_autotune import best_topk_mode

    return best_topk_mode(rank, "f32" if quant in (None, "off")
                          else quant)


@functools.partial(jax.jit, static_argnames=("k", "n_items"))
def _topk_scores(user_vecs: jax.Array, item_factors: jax.Array,
                 k: int, n_items: int) -> Tuple[jax.Array, jax.Array]:
    """Batched top-k over all items: [B, r] × [n_pad, r]ᵀ → scores+ids.
    Padded item rows are masked to -inf before ``lax.top_k``."""
    scores = user_vecs @ item_factors.T  # [B, n_pad] — MXU matmul
    n_pad = item_factors.shape[0]
    mask = jnp.arange(n_pad) < n_items
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k", "n_items"))
def _serve_topk(user_factors, item_factors, idx: jax.Array, *, k: int,
                n_items: int) -> Tuple[jax.Array, jax.Array]:
    """The WHOLE serving dispatch as one compiled program: user-row
    gather + [B, r]×[n_pad, r]ᵀ matmul + pad mask + top_k. Eagerly these
    were 4-5 separate dispatches, each a round trip through the device
    tunnel — fused, a query pays one dispatch and one fetch (measured:
    the per-query device path's p50 dropped ~4x).

    Tables may be :class:`QuantizedFactors`: rows upcast to f32 (and
    per-row scales apply) INSIDE the program, so the dot accumulates
    f32 while HBM holds int8/bf16 — the einsum realization of the
    serving-quant co-design. This is also the XLA reference the fused
    kernel (``ops/fused_topk.py``) is held exact against."""
    ud, us = _table_leaves(user_factors)
    vd, vs = _table_leaves(item_factors)
    # ptpu: allow[materialized-gather] — a [B, r] serving row fetch
    # (no history axis): bounded by the micro-batcher's pow2 batch cap
    vecs = ud[idx]
    if vecs.dtype != jnp.float32:
        vecs = vecs.astype(jnp.float32)
    if us is not None:
        # ptpu: allow[materialized-gather] — [B]-bounded scale fetch
        vecs = vecs * us.reshape(-1)[idx][:, None]
    if vd.dtype != jnp.float32:
        vd = vd.astype(jnp.float32)
    scores = vecs @ vd.T
    if vs is not None:
        # per-row item scales factor out of the dot: score[b,i] =
        # (vec·q_i)·s_i — applied to the [B, n_pad] product, never as
        # a dequantized f32 copy of the table
        scores = scores * vs.reshape(1, -1)
    n_pad = vd.shape[0]
    mask = jnp.arange(n_pad) < n_items
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k", "n_items"))
def _fused_topk_entry(user_table, item_table, idx, *, k: int,
                      n_items: int) -> Tuple[jax.Array, jax.Array]:
    """The fused-kernel serving dispatch as ONE named jit entry so the
    AOT seam (``predictionio_tpu.aot``) can lower/serialize it whole —
    the outer jit inlines the inner kernel jits, and quantized tables
    split into leaves inside the traced program exactly as
    :func:`_serve_topk` does."""
    from ..ops.fused_topk import fused_topk_dispatch

    ud, us = _table_leaves(user_table)
    vd, vs = _table_leaves(item_table)
    return fused_topk_dispatch(ud, idx, vd, us, vs, k=k,
                               n_items=n_items)


def _device_topk(user_table, item_table, idx: np.ndarray, k_dev: int,
                 n_items: int) -> Tuple[jax.Array, jax.Array]:
    """The single-device batched top-k dispatch switch (ISSUE 13):
    routes to the fused gather→score→top-k Pallas kernel
    (``ops/fused_topk.py`` — the [B, I] score matrix never lands in
    HBM) when the autotune table resolves "fused" and the compiled k
    fits the on-chip merge, else the :func:`_serve_topk` einsum
    program. Both realizations share tie semantics (descending score,
    lowest id first), so the switch is invisible to callers.

    Both realizations launch through :func:`aot.dispatch` — the seam
    that answers from a deserialized build-time executable when a warm
    artifact store is active (ISSUE 19), and is a plain tail call
    otherwise."""
    from .. import aot
    from ..ops.fused_topk import TOPK_MAX_K

    vd, vs = _table_leaves(item_table)
    mode = resolved_topk_mode(int(vd.shape[-1]), table_quant(item_table))
    if mode == "fused" and 1 <= k_dev <= TOPK_MAX_K:
        # the index stays uncommitted numpy (int32 — the kernel's SMEM
        # staging dtype): the jitted kernel places it, no eager
        # host→device hop for the transfer guard to flag
        out = aot.dispatch(
            "fused_topk", _fused_topk_entry,
            (user_table, item_table, np.asarray(idx, dtype=np.int32)),
            {"k": k_dev, "n_items": n_items})
    else:
        out = aot.dispatch(
            "serve_topk", _serve_topk, (user_table, item_table, idx),
            {"k": k_dev, "n_items": n_items})
    if _numerics.active():
        # debug_numerics: host NaN probe on the served scores (forces
        # the dispatch sync — the documented debug-mode cost);
        # nan_only because padded slots legitimately score -inf
        _numerics.check_array("serve_topk", out[0], nan_only=True)
    return out


#: serializes SHARDED serving dispatches process-wide. The mesh program
#: runs a collective (candidate all-gather) across every device: two
#: host threads enqueueing it concurrently can interleave their
#: per-device launches in different orders, and the collective
#: rendezvous deadlocks (observed as stuck AllGather participants on
#: the 8-device CPU mesh; the same launch-order hazard exists on real
#: meshes). The mesh is ONE resource — throughput comes from the
#: micro-batcher coalescing, not from concurrent mesh programs.
_mesh_dispatch_lock = threading.Lock()


def _is_row_sharded(arr) -> bool:
    """True when ``arr`` is a jax array whose rows are spread across
    more than one device (a :func:`shard_model` table) — its gathers
    must be GSPMD-resolved, never a host ``np.asarray``."""
    if isinstance(arr, QuantizedFactors):
        arr = arr.data
    sharding = getattr(arr, "sharding", None)
    if sharding is None:
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:  # noqa: BLE001 — exotic shardings: assume local
        return False


@functools.lru_cache(maxsize=16)
def _gather_rows_fn(mesh: Mesh):
    """Compile-once row gather from a row-sharded factor table to a
    REPLICATED [B, r] block: the GSPMD-inserted collective that
    resolves a cross-shard user-row fetch (the ALX serving gather).
    Output replicated so the per-shard ranking can consume it."""
    # ptpu: allow[materialized-gather] — [B, r] cross-shard row fetch
    # bounded by the serving batch; the sharded table itself never
    # materializes anywhere
    return jax.jit(lambda table, idx: table[idx],
                   out_shardings=NamedSharding(mesh, P()))


@functools.lru_cache(maxsize=16)
def _gather_vecs_fn(mesh: Mesh, has_scale: bool):
    """Quantized twin of :func:`_gather_rows_fn`: cross-shard row
    gather PLUS on-the-fly dequantization (upcast + per-row scale),
    output replicated — the int8/bf16 rows are what cross the ICI."""
    if has_scale:
        # ptpu: allow[materialized-gather] — [B, r] cross-shard row
        # fetch bounded by the serving batch (dequantized in-program)
        fn = (lambda table, scale, idx:
              table[idx].astype(jnp.float32) * scale[idx].reshape(-1, 1))
    else:
        # ptpu: allow[materialized-gather] — same [B, r] row fetch
        fn = lambda table, scale, idx: table[idx].astype(jnp.float32)
    return jax.jit(fn, out_shardings=NamedSharding(mesh, P()))


def _user_vecs(user_factors, user_indices: np.ndarray, mesh: Mesh):
    """[B, r] f32 query vectors for the sharded ranker, replicated over
    the mesh. Row-sharded tables gather via GSPMD collectives (the
    table never exists on one device) — quantized tables dequantize
    inside the same program; host/np tables gather locally. Host
    inputs stay UNCOMMITTED numpy so the mesh program places them
    itself — a ``jnp.asarray`` here would commit to device 0 and every
    dispatch would pay (and the transfer guard would flag) a
    device-to-device hop."""
    idx = np.asarray(user_indices, dtype=np.int64)
    ud, us = _table_leaves(user_factors)
    if _is_row_sharded(ud):
        if not isinstance(user_factors, QuantizedFactors):
            return _gather_rows_fn(mesh)(ud, idx)
        return _gather_vecs_fn(mesh, us is not None)(ud, us, idx)
    host = np.asarray(ud)[idx].astype(np.float32)
    if us is not None:
        host = host * np.asarray(us).reshape(-1)[idx][:, None]
    return host


def recommend_batch_sharded(user_factors, item_factors,
                            user_indices: np.ndarray, k: int,
                            mesh: Mesh, n_items: int
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Serving top-k over a device mesh — the multi-chip form of the
    reference's serving moment (``CreateServer.scala:508-510``): item
    factors ROW-SHARDED over every mesh device (a pod-scale catalog
    never lives on one chip), the query vectors replicated. User rows
    are first resolved — by a GSPMD-inserted collective gather when the
    user table is itself row-sharded (the >1-HBM regime), by a host
    gather otherwise. Each device then ranks its item shard locally
    ([B, n_local] matmul + local top_k) and the per-shard candidates
    are all-gathered and reduced to the global top-k — O(k·n_dev)
    cross-device traffic instead of O(n_items).

    Exact vs the single-device path for distinct scores (ties resolve
    by shard order rather than global index; float scores make exact
    ties measure-zero). Returns host (ids, scores) of shape [B, k].
    """
    n_dev = mesh.devices.size
    vd, _ = _table_leaves(item_factors)
    n_pad = vd.shape[0]
    if n_pad % n_dev:
        raise ValueError(f"item rows {n_pad} not divisible by mesh size "
                         f"{n_dev}; pad factors to a device multiple "
                         f"(shard_model does)")
    with _mesh_dispatch_lock:
        vecs = _user_vecs(user_factors, user_indices, mesh)
        # item_factors passes through UNPLACED when it is host data:
        # the mesh program shards it per in_specs; an eager jnp.asarray
        # would commit the whole table to device 0 first.
        ids, scores = _rank_sharded(mesh, vecs, item_factors, k,
                                    n_items)
        kk = min(k, n_items)
        ids, scores = jax.device_get((ids, scores))
    return ids[:, :kk], scores[:, :kk]


def _rank_sharded(mesh: Mesh, vecs, item_factors, k_dev: int,
                  n_items: int):
    """Launch the sharded ranking program for replicated [B, r] query
    vectors against a (possibly quantized) row-sharded item table —
    the shared entry of :func:`recommend_batch_sharded`,
    :func:`_dispatch_topk_chunk` and :func:`recommend_pinned`.
    Resolves the per-shard top-k realization (einsum vs the fused
    kernel) ONCE per (mesh, shape) via the compile-once cache.
    Callers hold ``_mesh_dispatch_lock``."""
    from ..ops.fused_topk import TOPK_MAX_K

    vd, vs = _table_leaves(item_factors)
    n_pad = vd.shape[0]
    k_local = min(k_dev, n_pad // mesh.devices.size)
    quant = table_quant(item_factors)
    mode = resolved_topk_mode(int(vd.shape[-1]), quant)
    if not (1 <= k_local <= TOPK_MAX_K):
        mode = "einsum"  # the on-chip merge carries k ≤ TOPK_MAX_K
    ranked = _sharded_rank_fn(mesh, k_dev, k_local, n_items, quant,
                              mode)
    # ptpu: allow[callback-under-lock] — `ranked` is a compiled XLA
    # executable (jit of shard_map), not user code: it cannot re-enter
    # the dispatch lock, and serializing the launch is the lock's
    # entire purpose (concurrent mesh-collective launches deadlock)
    dyn = (vecs, vd) if vs is None else (vecs, vd, vs)
    # key_extra mirrors the _sharded_rank_fn cache key: the argument
    # signature alone cannot distinguish two mesh programs that differ
    # only in k/k_local/topk realization
    from .. import aot
    return aot.dispatch(
        "sharded_rank", ranked, dyn,
        key_extra=(tuple(int(s) for s in mesh.devices.shape),
                   tuple(mesh.axis_names), k_dev, k_local, n_items,
                   quant or "off", mode))


@functools.lru_cache(maxsize=64)
def _sharded_rank_fn(mesh: Mesh, k: int, k_local: int, n_items: int,
                     quant: str = "off", topk_mode: str = "einsum"):
    """Compile-once cache for the sharded serving program (a fresh
    closure per call would defeat the jit cache and recompile the mesh
    program on every serving batch). Keyed on (mesh, k, k_local,
    n_items, quant, topk_mode); shapes key the inner jit cache as
    usual. Axis names come from the mesh, so the same program serves a
    ``(data, model)`` training mesh and the ``(batch, model)`` serving
    mesh.

    Each shard ranks its LOCAL item rows — through the fused
    gather→score→top-k kernel when ``topk_mode="fused"`` (the shard's
    [B, n_local] score block never lands in HBM; the shard's global id
    origin rides in as the kernel's ``base``), else the einsum + local
    top_k baseline with int8/bf16 rows dequantized in-program — then
    the per-shard candidates all-gather and reduce to the global
    top-k, exactly as before."""
    from ..parallel.collectives import shard_map_compat

    axes = tuple(mesh.axis_names)
    has_scale = quant == "int8"

    def local_rank(vecs, itf_local, isc_local=None):
        n_local = itf_local.shape[0]
        shard = jax.lax.axis_index(axes)
        base = shard * n_local
        if topk_mode == "fused":
            from ..ops.fused_topk import fused_topk_dispatch

            uscale = jnp.ones((vecs.shape[0], 1), jnp.float32) \
                if has_scale else None  # vecs arrive dequantized
            s, gid = fused_topk_dispatch(
                vecs, jnp.arange(vecs.shape[0], dtype=jnp.int32),
                itf_local, uscale, isc_local, base, k=k_local,
                n_items=n_items)
        else:
            itf = itf_local.astype(jnp.float32) \
                if itf_local.dtype != jnp.float32 else itf_local
            scores = vecs @ itf.T            # [B, n_local]
            if isc_local is not None:
                scores = scores * isc_local.reshape(1, -1)
            local_ids = base + jnp.arange(n_local)
            scores = jnp.where((local_ids < n_items)[None, :], scores,
                               -jnp.inf)
            s, i = jax.lax.top_k(scores, k_local)
            gid = jnp.take(local_ids, i)
        # gather the candidate sets along the candidate axis
        s_all = jax.lax.all_gather(s, axes, axis=1,
                                   tiled=True)  # [B, k_local*n_dev]
        g_all = jax.lax.all_gather(gid, axes, axis=1, tiled=True)
        s2, pos = jax.lax.top_k(s_all, s_all.shape[1])
        return jnp.take_along_axis(g_all, pos, axis=1)[:, :k], \
            s2[:, :k]

    spec = rows_spec(mesh)
    if has_scale:
        return jax.jit(shard_map_compat(
            local_rank, mesh, in_specs=(P(), spec, spec),
            out_specs=(P(), P()), check=False))
    return jax.jit(shard_map_compat(
        local_rank, mesh, in_specs=(P(), spec),
        out_specs=(P(), P()), check=False))


def _compiled_k(k: int, n_items: int) -> int:
    """Bound jit-cache growth on the serving path: the device kernel always
    runs with k rounded up to a power of two (clamped to the catalog), so
    arbitrary per-query ``num`` values reuse O(log n) compilations; callers
    slice the first ``k`` on the host."""
    k = min(k, n_items)
    p = 1
    while p < k:
        p <<= 1
    return min(p, n_items)


#: host-serving work budget in (batch × factor-matrix elements): under it,
#: serving runs on the HOST (numpy dot + sort, microseconds) instead of
#: paying a per-query device dispatch — SURVEY hard part 3: the reference
#: served from an in-JVM BLAS dot, and a small catalog never justifies
#: the dispatch (let alone a tunneled one). Large catalogs — or large
#: coalesced micro-batches over mid-size catalogs — stay on the MXU,
#: where the batched matmul wins.
HOST_SERVE_WORK = 64 * 1024 * 1024


def _host_topk(user_vecs: np.ndarray, item_factors: np.ndarray,
               k: int, n_items: int) -> Tuple[np.ndarray, np.ndarray]:
    """Exact host mirror of the device path: descending score, ties to
    the LOWEST item index (``lax.top_k`` semantics), so a model answers
    identically whichever path serves it."""
    scores = np.asarray(user_vecs) @ np.asarray(item_factors)[:n_items].T
    k = min(k, n_items)
    ids = np.empty((scores.shape[0], k), dtype=np.int64)
    out = np.empty((scores.shape[0], k), dtype=scores.dtype)
    idx_key = np.arange(n_items)
    for b in range(scores.shape[0]):
        order = np.lexsort((idx_key, -scores[b]))[:k]
        ids[b] = order
        out[b] = scores[b, order]
    return ids, out


def _serve_on_host(model: ALSModel, batch: int) -> bool:
    return (isinstance(model.item_factors, np.ndarray)
            and model.item_factors.size * max(batch, 1) <= HOST_SERVE_WORK)


def ensure_device_resident(model: ALSModel,
                           max_batch: int = 1) -> ALSModel:
    """Deploy-time factor placement: models past the host-serving
    budget move into HBM ONCE. A deployed model re-materialized from
    the blob store holds numpy factors, and the serving jits would
    otherwise re-transfer them on EVERY query (~42MB per query at
    ML-20M scale — fatal through a tunneled device). Small catalogs
    stay host-resident for the host fast path. ``max_batch`` is the
    largest serving batch this surface coalesces (the micro-batcher's
    cap, batch-predict's flush size): a mid-size catalog under the
    batch-1 budget but over the batched one serves on the DEVICE for
    big batches, so it must be device-resident too."""
    import dataclasses

    if _serve_on_host(model, batch=max(max_batch, 1)):
        return model

    def _has_host_leaf(t) -> bool:
        return any(isinstance(leaf, np.ndarray)
                   for leaf in jax.tree_util.tree_leaves(t))

    if _has_host_leaf(model.user_factors) \
            or _has_host_leaf(model.item_factors):
        # device_put maps over pytrees, so quantized tables move their
        # int8/bf16 data + f32 scale leaves in one shot
        return dataclasses.replace(
            model,
            user_factors=jax.device_put(model.user_factors),
            item_factors=jax.device_put(model.item_factors))
    return model


# -- mesh-wide serving placement (ISSUE 6) ----------------------------------

def _pad_rows(arr: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad the row axis to a device multiple (even shards)."""
    n = arr.shape[0]
    n_pad = -(-n // multiple) * multiple
    if n_pad == n:
        return arr
    out = np.zeros((n_pad,) + arr.shape[1:], dtype=arr.dtype)
    out[:n] = arr
    return out


def shard_model(model: ALSModel, mesh: Mesh) -> ALSModel:
    """SHARDED serving placement: both factor tables row-sharded over
    every device of the ``(batch, model)`` serving mesh via
    ``NamedSharding`` (ALX's row-sharded factor layout) — the table a
    single HBM cannot hold exists only as per-device shards. Rows are
    zero-padded to a device multiple; ``n_users``/``n_items`` keep the
    real counts so padding can never be served."""
    import dataclasses

    n_dev = mesh.devices.size
    spec = NamedSharding(mesh, rows_spec(mesh))

    def _place(t):
        if isinstance(t, QuantizedFactors):
            # quantized tables shard leaf-wise: int8/bf16 data and the
            # [n, 1] f32 scales land row-sharded together, so a shard
            # can dequantize its rows with no cross-device fetch
            data = np.asarray(jax.device_get(t.data))
            sc = None if t.scale is None \
                else np.asarray(jax.device_get(t.scale))
            return QuantizedFactors(
                jax.device_put(_pad_rows(data, n_dev), spec),
                None if sc is None
                else jax.device_put(_pad_rows(sc, n_dev), spec),
                t.quant)
        arr = np.asarray(t) if isinstance(t, np.ndarray) \
            else jax.device_get(t)
        return jax.device_put(_pad_rows(np.asarray(arr), n_dev), spec)

    return dataclasses.replace(
        model,
        user_factors=_place(model.user_factors),
        item_factors=_place(model.item_factors),
        mesh=mesh)


def replicate_model(model: ALSModel, device) -> ALSModel:
    """REPLICATED serving placement: one full copy of the factor tables
    committed to ``device`` — each replicated-mode lane owns a copy, so
    its dispatches compile and run on its own chip with no cross-device
    sync on the serve path."""
    import dataclasses

    return dataclasses.replace(
        model,
        user_factors=jax.device_put(model.user_factors, device),
        item_factors=jax.device_put(model.item_factors, device),
        mesh=None)


def pin_user_rows(model: ALSModel, user_indices: Sequence[int],
                  capacity: int) -> Tuple[Optional[jax.Array], int]:
    """Hot-entity tier (ISSUE 4): gather the given users' factor rows
    into ONE device-resident ``[capacity, rank]`` table. The table is
    padded to the FIXED capacity so its serving program compiles once
    per process — refreshes that re-rank the hot set reuse the same
    compiled shape instead of paying a post-warm trace per refresh.

    Returns ``(pinned_table, nbytes)``; ``(None, 0)`` for host-served
    models (the host fast path has no gather/transfer to skip).

    Sharded models (``model.mesh`` set) pin a mesh-REPLICATED table:
    the hot rows are fetched once through the GSPMD collective gather
    (the full table never lands on the host) and the [K, rank] result —
    tiny next to the sharded tables — is replicated so every device
    ranks hot users without a per-query cross-shard fetch."""
    if _serve_on_host(model, batch=1) or not len(user_indices):
        return None, 0
    cap = max(int(capacity), 1)
    idx = np.zeros(cap, dtype=np.int64)
    n = min(len(user_indices), cap)
    idx[:n] = np.asarray(list(user_indices)[:n], dtype=np.int64)
    mesh = getattr(model, "mesh", None)
    quant = isinstance(model.user_factors, QuantizedFactors)
    ud, us = _table_leaves(model.user_factors)
    if mesh is not None:
        with _mesh_dispatch_lock:
            # quantized models pin a QUANTIZED table (the hot tier
            # inherits the 4x capacity win); the collective gather
            # moves int8/bf16 rows + f32 scales, never a dequant copy
            rows_dev = _gather_rows_fn(mesh)(ud, idx)
            sc_dev = _gather_rows_fn(mesh)(us, idx) \
                if us is not None else None
            rows_dev.block_until_ready()
        if quant:
            pinned = QuantizedFactors(rows_dev, sc_dev,
                                      model.user_factors.quant)
            return pinned, pinned.nbytes
        return rows_dev, int(rows_dev.nbytes)
    if quant:
        data = np.asarray(jax.device_get(ud))[idx]
        sc = np.asarray(jax.device_get(us))[idx] \
            if us is not None else None
        pinned = QuantizedFactors(
            jax.device_put(data),
            None if sc is None else jax.device_put(sc),
            model.user_factors.quant)
        pinned.data.block_until_ready()
        return pinned, pinned.nbytes
    rows = np.asarray(model.user_factors)[idx]  # one host gather per
    pinned = jax.device_put(rows)               # refresh, not per query
    pinned.block_until_ready()
    return pinned, int(rows.nbytes)


def pin_user_rows_lanes(model: ALSModel, user_indices: Sequence[int],
                        capacity: int, devices: Sequence
                        ) -> Tuple[Optional[tuple], int]:
    """Replicated-mode hot tier: the SAME pinned ``[capacity, rank]``
    table committed once per lane device, so whichever lane serves a
    hot query gathers from its local copy (per-device pinned shards —
    no cross-device traffic on the pinned fast path). Returns
    ``(tables_per_device, total_nbytes)`` or ``(None, 0)``."""
    if _serve_on_host(model, batch=1) or not len(user_indices) \
            or not len(devices):
        return None, 0
    cap = max(int(capacity), 1)
    idx = np.zeros(cap, dtype=np.int64)
    n = min(len(user_indices), cap)
    idx[:n] = np.asarray(list(user_indices)[:n], dtype=np.int64)
    if isinstance(model.user_factors, QuantizedFactors):
        ud, us = _table_leaves(model.user_factors)
        data = np.asarray(jax.device_get(ud))[idx]
        sc = np.asarray(jax.device_get(us))[idx] \
            if us is not None else None
        tables = tuple(
            QuantizedFactors(
                jax.device_put(data, d),
                None if sc is None else jax.device_put(sc, d),
                model.user_factors.quant)
            for d in devices)
        for t in tables:
            t.data.block_until_ready()
        return tables, tables[0].nbytes * len(tables)
    rows = np.asarray(model.user_factors)[idx]
    tables = tuple(jax.device_put(rows, d) for d in devices)
    for t in tables:
        t.block_until_ready()
    return tables, int(rows.nbytes) * len(tables)


def recommend_pinned(model: ALSModel, pinned, slot: int,
                     k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k for one PINNED hot user: the row gather runs against the
    small HBM-resident pinned table instead of the full ``[U, rank]``
    factor matrix (which, for a re-materialized host-resident model,
    would cost a host gather + device transfer on every query).

    ``pinned`` may be a tuple of per-device tables (replicated lanes,
    :func:`pin_user_rows_lanes`) — the copy committed to the SAME
    device as ``model``'s factors is used, so a lane-rotated model
    (``QueryServer._dispatch_predictions``) serves hot queries fully
    lane-local. Sharded models rank the pinned vector through the mesh
    program (each device scores its item shard)."""
    if isinstance(pinned, tuple):
        chosen = pinned[0]
        try:
            devs = _table_leaves(model.item_factors)[0].devices()
            for t in pinned:
                if _table_leaves(t)[0].devices() == devs:
                    chosen = t
                    break
        except Exception:  # noqa: BLE001 — host-resident factors place
            pass           # with any copy; jit decides
        pinned = chosen
    mesh = getattr(model, "mesh", None)
    if mesh is not None:
        k_dev = _compiled_k(k, model.n_items)
        with _mesh_dispatch_lock:
            # ptpu: allow[callback-under-lock] — compiled XLA
            # executables (jitted gather + mesh ranker); they cannot
            # re-enter, and the lock exists to serialize their launch
            pd, ps = _table_leaves(pinned)
            sidx = np.asarray([slot], dtype=np.int64)
            if isinstance(pinned, QuantizedFactors):
                vec = _gather_vecs_fn(mesh, ps is not None)(pd, ps,
                                                            sidx)
            else:
                vec = _gather_rows_fn(mesh)(pd, sidx)  # [1, r]
            ids, scores = _rank_sharded(mesh, vec, model.item_factors,
                                        k_dev, model.n_items)
            k = min(k, model.n_items)
            ids, scores = jax.device_get((ids, scores))
        return ids[0][:k], scores[0][:k]
    k_dev = _compiled_k(k, model.n_items)
    scores, ids = _device_topk(
        pinned, model.item_factors,
        np.asarray([slot], dtype=np.int64), k_dev, model.n_items)
    k = min(k, model.n_items)
    ids, scores = jax.device_get((ids, scores))
    return ids[0][:k], scores[0][:k]


def recommend_products(model: ALSModel, user_index: int, k: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k (item_index, score) for one user — the
    ``ALSModel.recommendProducts`` role (``ALSAlgorithm.scala:95-109``).
    Like the reference, asking for more than the catalog returns the whole
    catalog ranked, never padded rows."""
    if getattr(model, "mesh", None) is not None:
        ids, scores = recommend_batch(
            model, np.asarray([user_index], dtype=np.int64), k)
        return ids[0], scores[0]
    if _serve_on_host(model, batch=1):
        ids, scores = _host_topk(
            np.asarray(model.user_factors)[user_index][None, :],
            model.item_factors, k, model.n_items)
        return ids[0], scores[0]
    k_dev = _compiled_k(k, model.n_items)
    # the index stays uncommitted numpy: jit places it beside the
    # (possibly lane-committed) factors with no device-to-device hop
    scores, ids = _device_topk(
        model.user_factors, model.item_factors,
        np.asarray([user_index], dtype=np.int64), k_dev,
        model.n_items)
    k = min(k, model.n_items)
    ids, scores = jax.device_get((ids, scores))
    return ids[0][:k], scores[0][:k]


#: device top-k rows per dispatch — bounds the [chunk, n_items]
#: score matrix (~230MB at ML-20M catalog) and keeps ONE compiled
#: shape for large eval sweeps
_TOPK_CHUNK = 2048


def _dispatch_topk_chunk(model: ALSModel, user_indices: np.ndarray,
                         k: int):
    """Enqueue ONE top-k device dispatch (batch ≤ ``_TOPK_CHUNK``) and
    return a no-arg resolver that blocks on the device arrays
    (``jax.device_get``) and hands back host ``([B, k] ids, scores)``.

    The dispatch half returns as soon as XLA has the executable
    enqueued — JAX async dispatch — so a staged serving pipeline can
    launch batch k+1 before batch k's results are read back (ISSUE 9).
    The batch axis pads to the pow2 ladder (every distinct [B, r]
    shape is a fresh XLA compile — measured ~10-20s each through the
    device tunnel) exactly as the synchronous path always did.

    Sharded models launch under ``_mesh_dispatch_lock`` as ever, but
    the readback runs OUTSIDE the lock: fetching an already-enqueued
    result is not a collective launch, so readers never serialize the
    NEXT batch's mesh dispatch behind a device→host transfer."""
    B = len(user_indices)
    kk = min(k, model.n_items)
    k_dev = _compiled_k(k, model.n_items)
    Bp = 1
    while Bp < B:
        Bp *= 2
    idx_dev = np.empty(Bp, dtype=np.int64)
    idx_dev[:B] = user_indices
    idx_dev[B:] = user_indices[0] if B else 0  # pad rows: any valid row
    mesh = getattr(model, "mesh", None)
    if mesh is not None:
        n_dev = mesh.devices.size
        n_pad = _table_leaves(model.item_factors)[0].shape[0]
        if n_pad % n_dev:
            raise ValueError(
                f"item rows {n_pad} not divisible by mesh size "
                f"{n_dev}; pad factors to a device multiple "
                f"(shard_model does)")
        with _mesh_dispatch_lock:
            vecs = _user_vecs(model.user_factors, idx_dev, mesh)
            ids, scores = _rank_sharded(mesh, vecs, model.item_factors,
                                        k_dev, model.n_items)
    else:
        scores, ids = _device_topk(
            model.user_factors, model.item_factors, idx_dev, k_dev,
            model.n_items)

    def resolve() -> Tuple[np.ndarray, np.ndarray]:
        i, s = jax.device_get((ids, scores))
        return i[:B, :kk], s[:B, :kk]

    return resolve


def recommend_batch_async(model: ALSModel, user_indices: np.ndarray,
                          k: int):
    """Dispatch/readback split of :func:`recommend_batch` (ISSUE 9):
    enqueues the device work and returns a no-arg resolver; calling it
    blocks until the results are on the host. Between the two calls
    the device computes while the caller is free to assemble and
    dispatch MORE batches — the continuous-batching serving pipeline's
    contract (docs/serving-pipeline.md).

    Host-served models compute inline (numpy is synchronous; there is
    nothing to overlap) and the resolver just returns the arrays.
    Batches past ``_TOPK_CHUNK`` dispatch every chunk up front — the
    device executes them back to back — and the resolver drains them
    in order."""
    user_indices = np.asarray(user_indices)
    B = len(user_indices)
    kk = min(k, model.n_items)
    if B == 0:
        empty = (np.empty((0, kk), np.int64),
                 np.empty((0, kk), np.float32))
        return lambda: empty
    if getattr(model, "mesh", None) is None \
            and _serve_on_host(model, batch=B):
        host = _host_topk(np.asarray(model.user_factors)[user_indices],
                          model.item_factors, k, model.n_items)
        return lambda: host
    resolvers = [
        _dispatch_topk_chunk(model, user_indices[s:s + _TOPK_CHUNK], k)
        for s in range(0, B, _TOPK_CHUNK)]
    if len(resolvers) == 1:
        return resolvers[0]

    def resolve() -> Tuple[np.ndarray, np.ndarray]:
        parts = [r() for r in resolvers]
        return (np.concatenate([p[0] for p in parts], axis=0),
                np.concatenate([p[1] for p in parts], axis=0))

    return resolve


def recommend_batch(model: ALSModel, user_indices: np.ndarray, k: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Micro-batched top-k for many users (one device dispatch, or the
    host path for small models + small batches). Sharded models
    (``model.mesh``) rank over the mesh: cross-shard user gather +
    per-device item-shard top-k + candidate merge, with the batch axis
    padded to the same pow2 ladder as the single-device path so the
    micro-batcher's arbitrary batch sizes reuse O(log) compilations.

    Realized as :func:`recommend_batch_async` + immediate readback so
    the synchronous and pipelined paths can never diverge."""
    return recommend_batch_async(model, user_indices, k)()


def _host_row_f32(t, i: int) -> np.ndarray:
    """One factor row as host f32, dequantizing if needed."""
    data, scale = _table_leaves(t)
    row = np.asarray(jax.device_get(data[i])).astype(np.float32)
    if scale is not None:
        row = row * float(np.asarray(jax.device_get(scale[i]))[0])
    return row


def predict_rating(model: ALSModel, user_index: int, item_index: int) -> float:
    u = _host_row_f32(model.user_factors, user_index)
    v = _host_row_f32(model.item_factors, item_index)
    return float(u @ v)


# -- streaming fold-in (ISSUE 10) --------------------------------------------
#
# The incremental-training primitives the StreamTrainer
# (predictionio_tpu/streaming/) folds fresh events in with: per-entity
# regularized least-squares solves against the FIXED opposite factor
# table — one half-iteration of ALS restricted to the affected rows.
# Because each row is re-solved from its FULL history, folding the same
# events in twice lands on the same row: replay after a crash is
# idempotent, which is what makes the cursor's at-least-once delivery
# effectively exactly-once (docs/streaming.md).

def dedupe_pairs(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Collapse repeated ``(row, col)`` pairs to the LAST value
    (last-write-wins, in input order). A burst of identical events must
    not multiply a pair's weight in the normal equations: under
    implicit ALS every duplicate adds another ``alpha·r`` of confidence
    for the SAME observation, and under explicit ALS the duplicated
    entry counts as extra evidence — both skew the fold-in relative to
    the batch trainer, whose input is one rating per (user, item)
    (regression-tested by tests/test_streaming.py)."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    if len(rows) == 0:
        return rows, cols, vals
    # np.unique keeps the FIRST occurrence per key; index from the back
    # so "first of reversed" is the last write
    key = np.stack([rows[::-1], cols[::-1]], axis=1)
    _, first_of_rev = np.unique(key, axis=0, return_index=True)
    keep = np.sort(len(rows) - 1 - first_of_rev)
    return rows[keep], cols[keep], vals[keep]


def fixed_gramian(fixed, params: "ALSParams"):
    """The implicit-path baseline Gramian FᵀF of the fixed side, for
    callers that amortize it across fold-in micro-batches (it depends
    only on the fixed table, not on which rows are being re-solved).
    Explicit models need none — returns None."""
    if not params.implicit_prefs:
        return None
    # a quantized serving table dequantizes once here (elementwise —
    # sharding preserved): fold-in math stays f32 against the same
    # values serving scores with
    arr = jnp.asarray(dequantize_table(fixed))
    bf16 = params.matmul_dtype == "bfloat16"
    if _is_row_sharded(arr):
        with _mesh_dispatch_lock:  # the reduction launches collectives
            return _fixed_gramian(arr, None, params.gram_mode, bf16)
    return _fixed_gramian(arr, None, params.gram_mode, bf16)


def _pow2_ceil(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


def fold_in_rows(fixed, indices: np.ndarray, values: np.ndarray,
                 counts: np.ndarray, params: "ALSParams",
                 G=None) -> np.ndarray:
    """Batched per-row fold-in: solve ``[B]`` rows' normal equations
    against the fixed opposite factor table — the streaming increment's
    device path. Routes through :func:`_update_block` (and therefore
    :func:`_lhs_fn`), so it shares the fused gather+Gramian kernel, the
    bf16 gather shadow and the implicit/explicit weighting with the
    batch trainer — the two solvers can never drift apart.

    ``indices``/``values`` are ``[B, L]`` histories (padding slots
    carry index 0 / value 0 and are masked by ``counts``). The batch
    and history axes pad to the pow2 ladder so arbitrary micro-batch
    shapes reuse O(log²) compilations. ``G`` (optional) is a
    precomputed fixed-side Gramian (:func:`fixed_gramian`); implicit
    callers that fold many micro-batches against one model should pass
    it rather than paying the O(n·r²) reduction per batch.

    Returns host ``[B, rank]`` f32 rows.
    """
    indices = np.asarray(indices, dtype=np.int32)
    values = np.asarray(values, dtype=np.float32)
    counts = np.asarray(counts, dtype=np.int32)
    B, L = indices.shape
    if B == 0:
        return np.zeros((0, fixed.shape[-1]), np.float32)
    Bp = _pow2_ceil(B)
    Lp = _pow2_ceil(max(L, 1), lo=8)
    idx = np.zeros((1, Bp, Lp), dtype=np.int32)
    val = np.zeros((1, Bp, Lp), dtype=np.float32)
    cnt = np.zeros((1, Bp), dtype=np.int32)
    idx[0, :B, :L] = indices
    val[0, :B, :L] = values
    cnt[0, :B] = counts
    implicit = params.implicit_prefs
    bf16 = params.matmul_dtype == "bfloat16"
    # quantized serving tables (ISSUE 13) dequantize for the solve —
    # the fold-in's normal equations stay f32 against the values the
    # table actually serves
    table = jnp.asarray(dequantize_table(fixed))

    def _solve():
        nonlocal G
        if implicit and G is None:
            G = _fixed_gramian(table, None, params.gram_mode, bf16)
        if not implicit:
            # static-arg shape filler, exactly like _update_side_split
            G = jnp.zeros((table.shape[-1],) * 2, jnp.float32)
        gsrc = table.astype(jnp.bfloat16) \
            if params.gather_dtype == "bfloat16" else table
        # debug_numerics routes the solve through checkify (NaN/Inf
        # attributed HERE, before a hot-swap can poison the serving
        # table); pass-through one bool check when off
        new = _numerics.checked_call(
            "fold_in_rows", _update_block, gsrc, G, idx, val, cnt,
            params.reg, params.alpha, implicit,
            params.scale_reg_by_count, bf16=bf16,
            gram=params.gram_mode, mesh=None)
        return np.asarray(jax.device_get(new[0][:B]), dtype=np.float32)

    if _is_row_sharded(table):
        # row-sharded serving table (ISSUE 6): GSPMD resolves the
        # gathers with collectives — launches must not interleave with
        # a concurrent serving dispatch's, exactly like recommend_*
        with _mesh_dispatch_lock:
            return _solve()
    return _solve()


def _scatter_rows(table: jax.Array, row_idx: np.ndarray,
                  rows: np.ndarray) -> jax.Array:
    """Functional device row update (NO donation: the previous table
    may still be serving through the old binding until the swap
    lands). The index axis pads to the pow2 ladder — duplicates of
    slot 0 re-write the same value, so padding is inert."""
    B = len(row_idx)
    Bp = _pow2_ceil(max(B, 1))
    idx = np.empty(Bp, dtype=np.int64)
    idx[:B] = row_idx
    idx[B:] = row_idx[0] if B else 0
    # rows keep their own dtype (int8/bf16 for re-quantized hot-swap
    # rows; f32 otherwise) — the jitted set casts to the table's
    vals = np.empty((Bp, rows.shape[-1]), dtype=rows.dtype)
    vals[:B] = rows
    vals[B:] = rows[0] if B else 0
    return _scatter_rows_fn(jnp.asarray(table), idx, vals)


@jax.jit
def _scatter_rows_fn(table: jax.Array, idx: jax.Array,
                     rows: jax.Array) -> jax.Array:
    return table.at[idx].set(rows.astype(table.dtype))


def apply_row_updates(model: ALSModel, side: str, row_idx: np.ndarray,
                      rows: np.ndarray) -> ALSModel:
    """A NEW model with ``side``'s factor rows at ``row_idx`` replaced
    by ``rows`` — the delta the streaming trainer hot-swaps into the
    serving binding. Purely functional: the input model (possibly still
    bound and serving) is never mutated, so a reader holding the old
    binding keeps a consistent table.

    Host-resident tables copy-and-write (numpy); device tables scatter
    through a compiled ``at[].set`` (no donation — see above); row-
    sharded tables run the same scatter under ``_mesh_dispatch_lock``
    (GSPMD keeps the output sharding) so a concurrent serving dispatch
    can't interleave collective launches."""
    import dataclasses

    if side not in ("user", "item"):
        raise ValueError(f"side must be 'user' or 'item', got {side!r}")
    name = "user_factors" if side == "user" else "item_factors"
    table = getattr(model, name)
    row_idx = np.asarray(row_idx, dtype=np.int64)
    rows = np.asarray(rows, dtype=np.float32)
    if len(row_idx) == 0:
        return model
    if isinstance(table, QuantizedFactors):
        # streaming hot-swap into a quantized serving table (ISSUE 13):
        # the freshly solved f32 rows RE-QUANTIZE on the way in — data
        # and per-row scales swap together, so a swapped row serves
        # with its own scale, never a stale one
        qd, qs = _quantize_rows(rows, table.quant)

        def _swap_leaves(data_new, scale_new):
            return dataclasses.replace(model, **{name: QuantizedFactors(
                data_new, scale_new, table.quant)})

        if isinstance(table.data, np.ndarray):
            data = table.data.copy()
            data[row_idx] = qd
            scale = None
            if table.scale is not None:
                scale = table.scale.copy()
                scale[row_idx] = qs
            return _swap_leaves(data, scale)
        if _is_row_sharded(table.data):
            with _mesh_dispatch_lock:
                data = _scatter_rows(table.data, row_idx, qd)
                data.block_until_ready()
                scale = None
                if table.scale is not None:
                    scale = _scatter_rows(table.scale, row_idx, qs)
                    scale.block_until_ready()
            return _swap_leaves(data, scale)
        data = _scatter_rows(table.data, row_idx, qd)
        scale = _scatter_rows(table.scale, row_idx, qs) \
            if table.scale is not None else None
        return _swap_leaves(data, scale)
    if isinstance(table, np.ndarray):
        new = table.copy()
        new[row_idx] = rows
    elif _is_row_sharded(table):
        with _mesh_dispatch_lock:
            new = _scatter_rows(table, row_idx, rows)
            new.block_until_ready()
    else:
        new = _scatter_rows(table, row_idx, rows)
    return dataclasses.replace(model, **{name: new})


#: cold-start capacity growth floor: when a side's table has no free
#: padding rows left, it grows by at least this many zero rows at once
#: so per-entity appends don't re-allocate (and re-place) the table on
#: every single new user/item
COLD_START_GROW_MIN = 64


def extend_factor_rows(model: ALSModel, side: str, new_keys: Sequence[str],
                       rows: np.ndarray) -> ALSModel:
    """Cold-start row insertion (ISSUE 10): register ``new_keys`` as
    fresh entities on ``side`` with the given factor rows. Training
    pads factor tables past ``n_users``/``n_items`` for even sharding —
    those zero padding rows are CLAIMED first (no reallocation, no new
    compiled serving shapes beyond the n_items bump); only when the
    table is full does it grow, by pow2-rounded chunks
    (:data:`COLD_START_GROW_MIN`), with the new capacity again zero-
    padded. Returns a new model: extended id map, bumped real count,
    rows written via :func:`apply_row_updates`."""
    import dataclasses

    from ..data.bimap import BiMap

    if side not in ("user", "item"):
        raise ValueError(f"side must be 'user' or 'item', got {side!r}")
    new_keys = list(new_keys)
    if not new_keys:
        return model
    name = "user_factors" if side == "user" else "item_factors"
    ids_name = "user_ids" if side == "user" else "item_ids"
    count_name = "n_users" if side == "user" else "n_items"
    table = getattr(model, name)
    ids = getattr(model, ids_name)
    n_real = getattr(model, count_name)
    rows = np.asarray(rows, dtype=np.float32)
    if rows.shape[0] != len(new_keys):
        raise ValueError(f"{len(new_keys)} keys but {rows.shape[0]} rows")
    for k in new_keys:
        if ids is not None and k in ids:
            raise ValueError(f"{side} {k!r} already indexed; fold in "
                             f"through apply_row_updates instead")
    n_after = n_real + len(new_keys)
    capacity = int(table.shape[0])
    if n_after > capacity:
        grow = _pow2_ceil(max(n_after - capacity, COLD_START_GROW_MIN))
        mesh = getattr(model, "mesh", None)

        def _grow_arr(arr, grow_n, fill):
            if isinstance(arr, np.ndarray):
                extra = np.full((grow_n,) + arr.shape[1:], fill,
                                arr.dtype)
                return np.concatenate([arr, extra], axis=0)
            if mesh is not None and _is_row_sharded(arr):
                # sharded growth: pull the shards together once,
                # extend to a device multiple, re-place row-sharded
                # (the same placement shard_model derives)
                host = jax.device_get(arr)
                host = np.concatenate(
                    [host, np.full((grow_n,) + host.shape[1:], fill,
                                   host.dtype)], axis=0)
                host = _pad_rows(host, mesh.devices.size)
                return jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
            pad = jnp.full((grow_n,) + arr.shape[1:], fill, arr.dtype)
            return jnp.concatenate([jnp.asarray(arr), pad], axis=0)

        if isinstance(table, QuantizedFactors):
            # claimed rows are re-quantized by the apply below; the
            # fresh capacity carries zero rows with scale 1 (inert)
            table = QuantizedFactors(
                _grow_arr(table.data, grow, 0),
                None if table.scale is None
                else _grow_arr(table.scale, grow, 1.0),
                table.quant)
        else:
            table = _grow_arr(table, grow, 0)
    fwd = dict(ids.items()) if ids is not None else {}
    for i, k in enumerate(new_keys):
        fwd[k] = n_real + i
    model = dataclasses.replace(
        model, **{name: table, ids_name: BiMap(fwd), count_name: n_after})
    return apply_row_updates(
        model, side, np.arange(n_real, n_after, dtype=np.int64), rows)
