"""Classification models: multinomial naive Bayes + random forest.

The role MLlib's ``NaiveBayes`` and ``RandomForest`` play for the
classification template (reference
``examples/scala-parallel-classification/add-algorithm/src/main/scala/
{NaiveBayesAlgorithm,RandomForestAlgorithm}.scala``).

TPU-first design:
- Naive Bayes: MLlib-compatible multinomial fit (additive ``lambda``
  smoothing over feature-value sums) producing a ``[C]`` log-prior vector
  and ``[C, F]`` log-likelihood matrix; batch predict is one jitted
  matmul + argmax (MXU work), not a per-point loop.
- Random forest: trees are grown host-side (tree induction is branchy,
  data-dependent control flow — exactly what XLA can't tile), but the
  fitted forest is ENCODED AS DENSE ARRAYS (feature / threshold /
  left / right / leaf-class per node, padded across trees) so inference
  is ``max_depth`` fused gathers under ``lax.fori_loop`` — fixed shapes,
  no host round-trips, batched over queries and trees at once.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np


# ---------------------------------------------------------------------------
# multinomial naive Bayes (MLlib NaiveBayes.train(data, lambda) parity)
# ---------------------------------------------------------------------------

@dataclass
class NaiveBayesModel:
    log_priors: np.ndarray       # [C]
    log_likelihoods: np.ndarray  # [C, F]
    classes: np.ndarray          # [C] original class labels (float/int)

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_scorer", None)
        return state

    def predict(self, features: Sequence[float]) -> float:
        x = np.asarray(features, dtype=np.float64)
        scores = self.log_priors + self.log_likelihoods @ x
        return float(self.classes[int(np.argmax(scores))])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        """[B, F] → [B] labels via one jitted matmul."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_scorer"):
            lp = jnp.asarray(self.log_priors, dtype=jnp.float32)
            ll = jnp.asarray(self.log_likelihoods, dtype=jnp.float32)
            # ptpu: allow[recompile-hazard] — jit built once per model
            # and cached on self; the captured arrays never change
            self._scorer = jax.jit(
                lambda x: jnp.argmax(x @ ll.T + lp, axis=1))
        idx = np.asarray(self._scorer(
            np.asarray(features, dtype=np.float32)))
        return self.classes[idx]


def train_naive_bayes_multinomial(features: np.ndarray, labels: np.ndarray,
                                  lam: float = 1.0) -> NaiveBayesModel:
    """MLlib multinomial NB: ``pi_c = log((N_c + λ)/(N + λC))``,
    ``theta_cf = log((Σ x_f|c + λ)/(Σ x|c + λF))``. Features must be
    non-negative (counts/one-hot)."""
    features = np.asarray(features, dtype=np.float64)
    labels = np.asarray(labels)
    if features.ndim != 2 or len(features) != len(labels):
        raise ValueError("features must be [N, F] aligned with labels")
    if (features < 0).any():
        raise ValueError("multinomial NB requires non-negative features")
    if lam <= 0:
        # λ=0 sends log(counts + λ) to -inf for any empty class/feature
        # and poisons every downstream score with NaN
        raise ValueError("lam (Laplace smoothing) must be positive")
    classes, class_idx = np.unique(labels, return_inverse=True)
    C, F = len(classes), features.shape[1]
    counts = np.bincount(class_idx, minlength=C).astype(np.float64)
    sums = np.zeros((C, F), dtype=np.float64)
    np.add.at(sums, class_idx, features)
    log_priors = np.log(counts + lam) - np.log(len(labels) + lam * C)
    log_likelihoods = (np.log(sums + lam)
                       - np.log(sums.sum(axis=1, keepdims=True) + lam * F))
    return NaiveBayesModel(log_priors, log_likelihoods, classes)


# ---------------------------------------------------------------------------
# random forest (MLlib RandomForest.trainClassifier parity)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RandomForestParams:
    num_classes: int = 2
    num_trees: int = 10
    feature_subset_strategy: str = "auto"  # auto|all|sqrt|log2|onethird
    impurity: str = "gini"
    max_depth: int = 5
    max_bins: int = 32
    seed: int = 0


class RandomForestModel:
    """Forest encoded as dense per-node arrays, padded across trees.

    ``feature[t, n] < 0`` marks a leaf whose class is ``leaf[t, n]``;
    internal nodes route to ``left/right[t, n]`` on
    ``x[feature] <= threshold``.
    """

    def __init__(self, feature: np.ndarray, threshold: np.ndarray,
                 left: np.ndarray, right: np.ndarray, leaf: np.ndarray,
                 classes: np.ndarray, max_depth: int):
        self.feature = feature      # [T, N] int32 (−1 = leaf)
        self.threshold = threshold  # [T, N] float32
        self.left = left            # [T, N] int32
        self.right = right          # [T, N] int32
        self.leaf = leaf            # [T, N] int32 (class index)
        self.classes = classes
        self.max_depth = max_depth

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_traverse", None)
        return state

    def predict(self, features: Sequence[float]) -> float:
        return float(self.predict_batch(
            np.asarray(features, dtype=np.float32)[None, :])[0])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        """[B, F] → [B] labels: fixed-depth vectorized traversal of all
        trees at once, majority vote."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        if not hasattr(self, "_traverse"):
            feat = jnp.asarray(self.feature)
            thr = jnp.asarray(self.threshold)
            lft = jnp.asarray(self.left)
            rgt = jnp.asarray(self.right)
            leaf = jnp.asarray(self.leaf)
            n_classes = len(self.classes)
            depth = self.max_depth + 1

            @jax.jit
            # ptpu: allow[recompile-hazard] — jit built once per model
            # and cached on self; the captured tree arrays never change
            def traverse(x):  # [B, F] → [B] class index
                B = x.shape[0]
                T = feat.shape[0]
                node = jnp.zeros((B, T), dtype=jnp.int32)

                def step(_, node):
                    f = jnp.take_along_axis(feat[None], node[..., None],
                                            axis=2)[..., 0]   # [B, T]
                    t = jnp.take_along_axis(thr[None], node[..., None],
                                            axis=2)[..., 0]
                    l = jnp.take_along_axis(lft[None], node[..., None],
                                            axis=2)[..., 0]
                    r = jnp.take_along_axis(rgt[None], node[..., None],
                                            axis=2)[..., 0]
                    xv = jnp.take_along_axis(
                        x, jnp.maximum(f, 0), axis=1)         # [B, T]
                    nxt = jnp.where(xv <= t, l, r)
                    return jnp.where(f < 0, node, nxt)

                node = lax.fori_loop(0, depth, step, node)
                cls = jnp.take_along_axis(leaf[None], node[..., None],
                                          axis=2)[..., 0]     # [B, T]
                votes = jax.nn.one_hot(cls, n_classes).sum(axis=1)
                return jnp.argmax(votes, axis=1)

            self._traverse = traverse
        idx = np.asarray(self._traverse(
            np.asarray(features, dtype=np.float32)))
        return self.classes[idx]


def _n_subset_features(strategy: str, n_features: int) -> int:
    if strategy in ("auto", "sqrt"):
        return max(1, int(np.sqrt(n_features)))
    if strategy == "log2":
        return max(1, int(np.log2(n_features)))
    if strategy == "onethird":
        return max(1, n_features // 3)
    return n_features  # "all"


def _gini(counts: np.ndarray) -> float:
    n = counts.sum()
    if n == 0:
        return 0.0
    p = counts / n
    return float(1.0 - (p * p).sum())


def _entropy(counts: np.ndarray) -> float:
    n = counts.sum()
    if n == 0:
        return 0.0
    p = counts[counts > 0] / n
    return float(-(p * np.log2(p)).sum())


def train_random_forest(features: np.ndarray, labels: np.ndarray,
                        params: RandomForestParams) -> RandomForestModel:
    """Bootstrap + per-node feature subsetting + binned threshold search
    (MLlib ``RandomForest.trainClassifier`` semantics at template scale)."""
    X = np.asarray(features, dtype=np.float32)
    y_raw = np.asarray(labels)
    classes, y = np.unique(y_raw, return_inverse=True)
    if len(classes) > params.num_classes:
        raise ValueError(
            f"found {len(classes)} distinct labels but num_classes="
            f"{params.num_classes} (MLlib trainClassifier validates this)")
    n, F = X.shape
    C = len(classes)
    impurity_fn = _gini if params.impurity == "gini" else _entropy
    rng = np.random.default_rng(params.seed)
    k_feats = _n_subset_features(params.feature_subset_strategy, F)

    trees = []
    for _ in range(params.num_trees):
        sample = rng.integers(0, n, n)  # bootstrap
        nodes = {"feature": [], "threshold": [], "left": [], "right": [],
                 "leaf": []}

        def new_node():
            for v in nodes.values():
                v.append(0)
            nodes["feature"][-1] = -1
            return len(nodes["feature"]) - 1

        def grow(idx: np.ndarray, depth: int) -> int:
            me = new_node()
            counts = np.bincount(y[idx], minlength=C).astype(np.float64)
            majority = int(np.argmax(counts))
            nodes["leaf"][me] = majority
            if depth >= params.max_depth or len(np.unique(y[idx])) <= 1 \
                    or len(idx) < 2:
                return me
            parent_imp = impurity_fn(counts)
            best = (0.0, None, None)  # (gain, feature, threshold)
            for f in rng.choice(F, size=k_feats, replace=False):
                vals = X[idx, f]
                uniq = np.unique(vals)
                if len(uniq) <= 1:
                    continue
                if len(uniq) > params.max_bins:
                    qs = np.quantile(vals, np.linspace(0, 1,
                                                       params.max_bins + 1)
                                     [1:-1])
                    cand = np.unique(qs)
                else:
                    cand = (uniq[:-1] + uniq[1:]) / 2
                for t in cand:
                    mask = vals <= t
                    nl = mask.sum()
                    if nl == 0 or nl == len(idx):
                        continue
                    cl = np.bincount(y[idx[mask]], minlength=C)
                    cr = counts - cl
                    gain = parent_imp - (
                        nl / len(idx) * impurity_fn(cl.astype(np.float64))
                        + (1 - nl / len(idx))
                        * impurity_fn(cr.astype(np.float64)))
                    if gain > best[0]:
                        best = (gain, int(f), float(t))
            if best[1] is None:
                return me
            _, f, t = best
            mask = X[idx, f] <= t
            li = grow(idx[mask], depth + 1)
            ri = grow(idx[~mask], depth + 1)
            nodes["feature"][me] = f
            nodes["threshold"][me] = t
            nodes["left"][me] = li
            nodes["right"][me] = ri
            return me

        grow(sample, 0)
        trees.append(nodes)

    max_nodes = max(len(t["feature"]) for t in trees)
    T = len(trees)
    feature = np.full((T, max_nodes), -1, dtype=np.int32)
    threshold = np.zeros((T, max_nodes), dtype=np.float32)
    left = np.zeros((T, max_nodes), dtype=np.int32)
    right = np.zeros((T, max_nodes), dtype=np.int32)
    leaf = np.zeros((T, max_nodes), dtype=np.int32)
    for ti, t in enumerate(trees):
        m = len(t["feature"])
        feature[ti, :m] = t["feature"]
        threshold[ti, :m] = t["threshold"]
        left[ti, :m] = t["left"]
        right[ti, :m] = t["right"]
        leaf[ti, :m] = t["leaf"]
    return RandomForestModel(feature, threshold, left, right, leaf,
                             classes, params.max_depth)
