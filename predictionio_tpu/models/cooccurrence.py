"""Item-item co-occurrence top-N.

Behavior parity with the similar-product template's
``CooccurrenceAlgorithm.trainCooccurrence``
(``examples/scala-parallel-similarproduct/multi-events-multi-algos/src/
main/scala/CooccurrenceAlgorithm.scala:71-104``): distinct (user, item)
pairs, co-occurrence count per unordered item pair, top-N neighbors per
item.

TPU-first design: where the reference self-joins an RDD (a shuffle), the
co-occurrence matrix is ``AᵀA`` for the binary user×item incidence
matrix — one bfloat16-friendly matmul on the MXU, diagonal zeroed, then
``lax.top_k`` per row. Falls back to a host sparse path when the dense
incidence matrix would not fit memory.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

# the dense MXU path materializes an [n_users, n_items] incidence matrix
# AND an [n_items, n_items] gram matrix; beyond this many cells in either,
# use the per-row sparse accumulation path (O(Σ basket²) time, O(row) memory)
_DENSE_CELL_LIMIT = 64 * 1024 * 1024


class CooccurrenceModel:
    def __init__(self, indices: np.ndarray, counts: np.ndarray,
                 n_items: int, top_n: int):
        #: [I, top_n] neighbor item index (−1 = pad)
        self.indices = indices
        #: [I, top_n] co-occurrence count (0 at pads)
        self.counts = counts
        self.n_items = n_items
        self.n = top_n

    def neighbors(self, item: int) -> List[Tuple[int, int]]:
        keep = self.indices[item] >= 0
        return list(zip(self.indices[item][keep].tolist(),
                        self.counts[item][keep].astype(int).tolist()))

    def score_items(self, query_items: Sequence[int]) -> Dict[int, float]:
        """Sum neighbor counts over the query items
        (``CooccurrenceAlgorithm.predict`` :120-126)."""
        out: Dict[int, float] = {}
        for q in query_items:
            if 0 <= q < self.n_items:
                for j, c in self.neighbors(q):
                    out[j] = out.get(j, 0.0) + c
        return out


def train_cooccurrence(users: np.ndarray, items: np.ndarray,
                       n_users: int, n_items: int,
                       top_n: int) -> CooccurrenceModel:
    """users/items: parallel arrays of (user idx, item idx) view events."""
    users = np.asarray(users, dtype=np.int64)
    items = np.asarray(items, dtype=np.int64)
    # distinct (user, item): multiple views count once (reference :83-85)
    pairs = np.unique(users * np.int64(n_items) + items)
    pu = pairs // n_items
    pi = (pairs % n_items).astype(np.int64)

    if (n_users * n_items <= _DENSE_CELL_LIMIT
            and n_items * n_items <= _DENSE_CELL_LIMIT):
        cooc = _dense_cooccurrence(pu, pi, n_users, n_items)
        np.fill_diagonal(cooc, 0)
        k = min(top_n, max(n_items - 1, 1))
        indices, counts = _topk_rows(cooc, k)
        # mask zero-count neighbors as pads
        indices = np.where(counts > 0, indices, -1).astype(np.int32)
        counts = np.where(counts > 0, counts, 0)
        return CooccurrenceModel(indices, counts, n_items, top_n)
    return _sparse_topn(pu, pi, n_items, top_n)


def _dense_cooccurrence(pu: np.ndarray, pi: np.ndarray, n_users: int,
                        n_items: int) -> np.ndarray:
    """AᵀA on device — the matmul IS the co-occurrence computation."""
    import jax
    import jax.numpy as jnp

    A = np.zeros((n_users, n_items), dtype=np.float32)
    A[pu, pi] = 1.0

    @jax.jit
    def gram(a):
        return a.T @ a

    return np.array(gram(jnp.asarray(A)))  # writable host copy


def _sparse_topn(pu: np.ndarray, pi: np.ndarray, n_items: int,
                 top_n: int) -> CooccurrenceModel:
    """Host path for large catalogs: per-item neighbor dicts, never a
    dense matrix. Memory is O(distinct co-occurring pairs)."""
    from collections import defaultdict

    order = np.argsort(pu, kind="stable")
    pu, pi = pu[order], pi[order]
    starts = np.flatnonzero(np.r_[True, pu[1:] != pu[:-1]])
    ends = np.r_[starts[1:], len(pu)]
    neigh: Dict[int, Dict[int, int]] = defaultdict(lambda: defaultdict(int))
    for s, e in zip(starts, ends):
        basket = pi[s:e].tolist()
        for a in basket:
            row = neigh[a]
            for b in basket:
                if b != a:
                    row[b] += 1
    indices = np.full((n_items, top_n), -1, dtype=np.int32)
    counts = np.zeros((n_items, top_n), dtype=np.float32)
    for a, row in neigh.items():
        # descending count, ties by lower item index (stable like the
        # dense top_k)
        top = sorted(row.items(), key=lambda kv: (-kv[1], kv[0]))[:top_n]
        for j, (b, c) in enumerate(top):
            indices[a, j] = b
            counts[a, j] = c
    return CooccurrenceModel(indices, counts, n_items, top_n)


def _topk_rows(matrix: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def topk(m):
        vals, idx = lax.top_k(m, k)
        return idx, vals

    idx, vals = topk(jnp.asarray(matrix))
    return np.asarray(idx), np.asarray(vals)
