"""Event-log → training-matrix conversion helpers.

The host-side bridge from string-keyed events to dense integer COO
(SURVEY §7 hard part 2): the role the templates' RDD maps + ``BiMap``
indexation played (``tests/pio_tests/engines/recommendation-engine/src/
main/scala/DataSource.scala:39-106``, ``ALSAlgorithm.scala:51-74``).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from ..data.bimap import BiMap
from ..data.event import Event
from .als import RatingsCOO


def ratings_from_events(
        events: Iterable[Event],
        event_weights: Optional[Dict[str, Optional[float]]] = None,
        user_ids: Optional[BiMap] = None,
        item_ids: Optional[BiMap] = None,
) -> Tuple[RatingsCOO, BiMap, BiMap]:
    """Turn rate/buy-style events into COO ratings + id maps.

    ``event_weights`` maps event name → fixed rating (None ⇒ read the
    ``rating`` property), mirroring the reference DataSource's handling of
    ``rate`` (explicit rating) and ``buy`` (implied rating 4.0,
    ``DataSource.scala:47-60``). Later duplicates are kept as separate
    entries (MLlib parity: ALS sees repeated pairs).
    """
    if event_weights is None:
        event_weights = {"rate": None, "buy": 4.0}

    users, items, vals = [], [], []
    for e in events:
        if e.event not in event_weights:
            continue
        if e.target_entity_id is None:
            continue
        w = event_weights[e.event]
        if w is None:
            w = e.properties.get("rating", float, default=None)
            if w is None:
                continue
        users.append(e.entity_id)
        items.append(e.target_entity_id)
        vals.append(float(w))

    if user_ids is None:
        user_ids = BiMap.string_int(users)
    if item_ids is None:
        item_ids = BiMap.string_int(items)

    u = user_ids.map_array(users)
    i = item_ids.map_array(items)
    v = np.asarray(vals, dtype=np.float32)
    keep = (u >= 0) & (i >= 0)
    return (RatingsCOO(u[keep].astype(np.int32), i[keep].astype(np.int32),
                       v[keep], len(user_ids), len(item_ids)),
            user_ids, item_ids)


def ratings_from_columnar(
        batch,
        event_weights: Optional[Dict[str, Optional[float]]] = None,
        user_ids: Optional[BiMap] = None,
        item_ids: Optional[BiMap] = None,
) -> Tuple[RatingsCOO, BiMap, BiMap]:
    """Vectorized :func:`ratings_from_events` over a
    :class:`~predictionio_tpu.data.columnar.ColumnarBatch` — no per-event
    Python objects anywhere on the training read path (the fix for
    VERDICT r1's top gap; role of ``ALSAlgorithm.scala:51-74``'s RDD maps).

    Semantics match the row version: later duplicates kept, events with a
    ``None`` weight read the ``rating`` float property (rows without one
    are dropped), ids absent from provided BiMaps are dropped.
    """
    if event_weights is None:
        event_weights = {"rate": None, "buy": 4.0}

    d = batch.dicts
    n = batch.n
    vals = np.full(n, np.nan, dtype=np.float64)
    sel = np.zeros(n, dtype=bool)
    for name, w in event_weights.items():
        code = d.event_names.index.get(name)
        if code is None:
            continue
        m = batch.event == code
        if w is None:
            col = batch.float_prop("rating")
            vals = np.where(m, col, vals)
            sel |= m & ~np.isnan(col)
        else:
            vals = np.where(m, float(w), vals)
            sel |= m
    sel &= batch.target_id >= 0

    u_codes = batch.entity_id[sel]
    i_codes = batch.target_id[sel]
    v = vals[sel].astype(np.float32)

    def densify(codes: np.ndarray, sd, ids: Optional[BiMap]):
        if ids is None:
            # bincount beats np.unique (no sort): codes are small dense
            # dictionary ints
            counts = np.bincount(codes, minlength=len(sd)) \
                if len(codes) else np.zeros(len(sd), dtype=np.int64)
            uniq = np.flatnonzero(counts)
            lut = np.full(max(len(sd), 1), -1, dtype=np.int64)
            lut[uniq] = np.arange(len(uniq))
            inv = lut[codes] if len(codes) else np.empty(0, np.int64)
            values = sd.values
            return BiMap({values[c]: j for j, c in enumerate(uniq)}), \
                inv, None
        lut = np.full(max(len(sd), 1), -1, dtype=np.int64)
        for s, j in ids.items():
            c = sd.index.get(s)
            if c is not None:
                lut[c] = j
        mapped = lut[codes] if len(codes) else \
            np.empty(0, dtype=np.int64)
        return ids, mapped, mapped >= 0

    user_ids, u, keep_u = densify(u_codes, d.entity_ids, user_ids)
    item_ids, i, keep_i = densify(i_codes, d.target_ids, item_ids)
    keep = None
    if keep_u is not None:
        keep = keep_u
    if keep_i is not None:
        keep = keep_i if keep is None else (keep & keep_i)
    if keep is not None:
        u, i, v = u[keep], i[keep], v[keep]
    return (RatingsCOO(u.astype(np.int32), i.astype(np.int32), v,
                       len(user_ids), len(item_ids)),
            user_ids, item_ids)


def kfold_split(n: int, k: int, seed: int = 0) -> list:
    """Index masks for k-fold cross-validation over COO entries (the
    ``e2/evaluation/CrossValidation.scala:24`` role)."""
    rng = np.random.default_rng(seed)
    fold_of = rng.integers(0, k, size=n)
    return [(fold_of != f, fold_of == f) for f in range(k)]
