"""Event-log → training-matrix conversion helpers.

The host-side bridge from string-keyed events to dense integer COO
(SURVEY §7 hard part 2): the role the templates' RDD maps + ``BiMap``
indexation played (``tests/pio_tests/engines/recommendation-engine/src/
main/scala/DataSource.scala:39-106``, ``ALSAlgorithm.scala:51-74``).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from ..data.bimap import BiMap
from ..data.event import Event
from .als import RatingsCOO


def ratings_from_events(
        events: Iterable[Event],
        event_weights: Optional[Dict[str, Optional[float]]] = None,
        user_ids: Optional[BiMap] = None,
        item_ids: Optional[BiMap] = None,
) -> Tuple[RatingsCOO, BiMap, BiMap]:
    """Turn rate/buy-style events into COO ratings + id maps.

    ``event_weights`` maps event name → fixed rating (None ⇒ read the
    ``rating`` property), mirroring the reference DataSource's handling of
    ``rate`` (explicit rating) and ``buy`` (implied rating 4.0,
    ``DataSource.scala:47-60``). Later duplicates are kept as separate
    entries (MLlib parity: ALS sees repeated pairs).
    """
    if event_weights is None:
        event_weights = {"rate": None, "buy": 4.0}

    users, items, vals = [], [], []
    for e in events:
        if e.event not in event_weights:
            continue
        if e.target_entity_id is None:
            continue
        w = event_weights[e.event]
        if w is None:
            w = e.properties.get("rating", float, default=None)
            if w is None:
                continue
        users.append(e.entity_id)
        items.append(e.target_entity_id)
        vals.append(float(w))

    if user_ids is None:
        user_ids = BiMap.string_int(users)
    if item_ids is None:
        item_ids = BiMap.string_int(items)

    u = user_ids.map_array(users)
    i = item_ids.map_array(items)
    v = np.asarray(vals, dtype=np.float32)
    keep = (u >= 0) & (i >= 0)
    return (RatingsCOO(u[keep].astype(np.int32), i[keep].astype(np.int32),
                       v[keep], len(user_ids), len(item_ids)),
            user_ids, item_ids)


def kfold_split(n: int, k: int, seed: int = 0) -> list:
    """Index masks for k-fold cross-validation over COO entries (the
    ``e2/evaluation/CrossValidation.scala:24`` role)."""
    rng = np.random.default_rng(seed)
    fold_of = rng.integers(0, k, size=n)
    return [(fold_of != f, fold_of == f) for f in range(k)]
