"""Event-log → training-matrix conversion helpers.

The host-side bridge from string-keyed events to dense integer COO
(SURVEY §7 hard part 2): the role the templates' RDD maps + ``BiMap``
indexation played (``tests/pio_tests/engines/recommendation-engine/src/
main/scala/DataSource.scala:39-106``, ``ALSAlgorithm.scala:51-74``).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from ..data.bimap import BiMap
from ..data.event import Event
from .als import RatingsCOO


def ratings_from_events(
        events: Iterable[Event],
        event_weights: Optional[Dict[str, Optional[float]]] = None,
        user_ids: Optional[BiMap] = None,
        item_ids: Optional[BiMap] = None,
) -> Tuple[RatingsCOO, BiMap, BiMap]:
    """Turn rate/buy-style events into COO ratings + id maps.

    ``event_weights`` maps event name → fixed rating (None ⇒ read the
    ``rating`` property), mirroring the reference DataSource's handling of
    ``rate`` (explicit rating) and ``buy`` (implied rating 4.0,
    ``DataSource.scala:47-60``). Later duplicates are kept as separate
    entries (MLlib parity: ALS sees repeated pairs).
    """
    if event_weights is None:
        event_weights = {"rate": None, "buy": 4.0}

    users, items, vals = [], [], []
    for e in events:
        if e.event not in event_weights:
            continue
        if e.target_entity_id is None:
            continue
        w = event_weights[e.event]
        if w is None:
            w = e.properties.get("rating", float, default=None)
            if w is None:
                continue
        users.append(e.entity_id)
        items.append(e.target_entity_id)
        vals.append(float(w))

    if user_ids is None:
        user_ids = BiMap.string_int(users)
    if item_ids is None:
        item_ids = BiMap.string_int(items)

    u = user_ids.map_array(users)
    i = item_ids.map_array(items)
    v = np.asarray(vals, dtype=np.float32)
    keep = (u >= 0) & (i >= 0)
    return (RatingsCOO(u[keep].astype(np.int32), i[keep].astype(np.int32),
                       v[keep], len(user_ids), len(item_ids)),
            user_ids, item_ids)


def ratings_from_columnar(
        batch,
        event_weights: Optional[Dict[str, Optional[float]]] = None,
        user_ids: Optional[BiMap] = None,
        item_ids: Optional[BiMap] = None,
) -> Tuple[RatingsCOO, BiMap, BiMap]:
    """Vectorized :func:`ratings_from_events` over a
    :class:`~predictionio_tpu.data.columnar.ColumnarBatch` — no per-event
    Python objects anywhere on the training read path (the fix for
    VERDICT r1's top gap; role of ``ALSAlgorithm.scala:51-74``'s RDD maps).

    Semantics match the row version: later duplicates kept, events with a
    ``None`` weight read the ``rating`` float property (rows without one
    are dropped), ids absent from provided BiMaps are dropped.
    """
    if event_weights is None:
        event_weights = {"rate": None, "buy": 4.0}

    d = batch.dicts
    by_code = {d.event_names.index[nm]: w
               for nm, w in event_weights.items()
               if nm in d.event_names.index}
    needs_prop = any(w is None for w in by_code.values())
    sel, vals = rating_selection(
        batch.event, batch.target_id,
        batch.float_prop("rating") if needs_prop else None, by_code)

    u_codes = batch.entity_id[sel]
    i_codes = batch.target_id[sel]
    v = vals[sel].astype(np.float32)

    def densify(codes: np.ndarray, sd, ids: Optional[BiMap]):
        if ids is None:
            # bincount beats np.unique (no sort): codes are small dense
            # dictionary ints
            counts = np.bincount(codes, minlength=len(sd)) \
                if len(codes) else np.zeros(len(sd), dtype=np.int64)
            uniq = np.flatnonzero(counts)
            lut = np.full(max(len(sd), 1), -1, dtype=np.int64)
            lut[uniq] = np.arange(len(uniq))
            inv = lut[codes] if len(codes) else np.empty(0, np.int64)
            values = sd.values
            return BiMap({values[c]: j for j, c in enumerate(uniq)}), \
                inv, None
        lut = np.full(max(len(sd), 1), -1, dtype=np.int64)
        for s, j in ids.items():
            c = sd.index.get(s)
            if c is not None:
                lut[c] = j
        mapped = lut[codes] if len(codes) else \
            np.empty(0, dtype=np.int64)
        return ids, mapped, mapped >= 0

    user_ids, u, keep_u = densify(u_codes, d.entity_ids, user_ids)
    item_ids, i, keep_i = densify(i_codes, d.target_ids, item_ids)
    keep = None
    if keep_u is not None:
        keep = keep_u
    if keep_i is not None:
        keep = keep_i if keep is None else (keep & keep_i)
    if keep is not None:
        u, i, v = u[keep], i[keep], v[keep]
    return (RatingsCOO(u.astype(np.int32), i.astype(np.int32), v,
                       len(user_ids), len(item_ids)),
            user_ids, item_ids)


def kfold_split(n: int, k: int, seed: int = 0) -> list:
    """Index masks for k-fold cross-validation over COO entries (the
    ``e2/evaluation/CrossValidation.scala:24`` role)."""
    rng = np.random.default_rng(seed)
    fold_of = rng.integers(0, k, size=n)
    return [(fold_of != f, fold_of == f) for f in range(k)]


# ---------------------------------------------------------------------------
# Sharded rating sources (multi-host partial reads)
# ---------------------------------------------------------------------------


def rating_selection(event_col, target_col, rating_col,
                     weights_by_code: Dict[int, Optional[float]]):
    """Shared event-selection/weight semantics of the training read:
    fixed-weight events always select; None-weight events read the
    ``rating`` float column and drop NaN rows; rows without a target
    never select. BOTH the one-shot COO conversion
    (:func:`ratings_from_columnar`) and the sharded source
    (:class:`ColumnarRatingsSource`) call this — the multihost
    v2-vs-v1 factor-equivalence guarantee rests on the two paths
    agreeing exactly.

    Returns ``(sel bool [n], vals float64 [n])`` (vals NaN outside
    ``sel``; ``rating_col`` may be None when no event needs it)."""
    ev = np.asarray(event_col)
    n = len(ev)
    sel = np.zeros(n, dtype=bool)
    vals = np.full(n, np.nan, dtype=np.float64)
    for code, w in weights_by_code.items():
        m = ev == code
        if w is None:
            assert rating_col is not None, \
                "None-weight events need the rating column"
            col = np.asarray(rating_col)
            vals = np.where(m, col, vals)
            sel |= m & ~np.isnan(col)
        else:
            vals = np.where(m, float(w), vals)
            sel |= m
    sel &= np.asarray(target_col) >= 0
    return sel, vals


class ColumnarRatingsSource:
    """Per-shard rating reads straight off a (mmap-backed)
    :class:`~predictionio_tpu.data.columnar.ColumnarBatch` — the
    multi-host feeding contract v2 (the ``JDBCPEvents.scala:49-89``
    partitioned-scan role): each pod host materializes ONLY the rating
    triples whose factor-row index falls in its shard, instead of every
    host holding the whole log's COO. Persistent per-host state is one
    bool mask + the code→index LUTs; everything else streams through
    ``chunk``-bounded temporaries over the mmap'd columns.

    All hosts derive IDENTICAL id indexation (BiMaps) from the same
    global batch, so shards assemble into one consistent model.
    """

    def __init__(self, batch,
                 event_weights: Optional[Dict[str, Optional[float]]] = None,
                 chunk: int = 4_000_000, count_reduce=None):
        self.batch = batch
        self.chunk = chunk
        #: global storage-row index of this batch's first row (a shard
        #: view sets it from the storage layer's ``shard_offset``)
        self._pos_base = 0
        if event_weights is None:
            event_weights = {"rate": None, "buy": 4.0}
        self._weights = event_weights
        d = batch.dicts
        # entry mask + values via the SAME helper the one-shot COO
        # conversion uses (rating_selection — semantic drift between the
        # two paths would silently break multihost shard equivalence)
        self._fixed = {d.event_names.index[nm]: w
                       for nm, w in event_weights.items()
                       if nm in d.event_names.index}
        needs_prop = any(w is None for w in self._fixed.values())
        sel, _ = rating_selection(
            batch.event, batch.target_id,
            batch.float_prop("rating") if needs_prop else None,
            self._fixed)
        self._sel = sel
        self._needs_prop = needs_prop
        # global id indexation: dictionary code -> dense factor row, in
        # first-appearance order of the OBSERVED codes (deterministic on
        # every host — same batch, same order). ``count_reduce`` (an
        # allreduce over processes) turns per-shard code counts into the
        # GLOBAL counts, so hosts holding different storage shards still
        # derive identical indexation — the batch's dictionaries are
        # log-global by construction, codes mean the same everywhere.
        u_counts = np.bincount(np.asarray(batch.entity_id)[sel],
                               minlength=max(len(d.entity_ids), 1))
        i_counts = np.bincount(np.asarray(batch.target_id)[sel],
                               minlength=max(len(d.target_ids), 1))
        if count_reduce is not None:
            u_counts = count_reduce(u_counts)
            i_counts = count_reduce(i_counts)
        u_uniq = np.flatnonzero(u_counts)
        i_uniq = np.flatnonzero(i_counts)
        self._u_lut = np.full(max(len(d.entity_ids), 1), -1, np.int64)
        self._u_lut[u_uniq] = np.arange(len(u_uniq))
        self._i_lut = np.full(max(len(d.target_ids), 1), -1, np.int64)
        self._i_lut[i_uniq] = np.arange(len(i_uniq))
        uv, iv = d.entity_ids.values, d.target_ids.values
        self.user_ids = BiMap({uv[c]: j for j, c in enumerate(u_uniq)})
        self.item_ids = BiMap({iv[c]: j for j, c in enumerate(i_uniq)})
        self.n_users = len(u_uniq)
        self.n_items = len(i_uniq)
        self._u_counts = u_counts[u_uniq]
        self._i_counts = i_counts[i_uniq]

    def row_counts(self, side: str) -> np.ndarray:
        return self._u_counts if side == "user" else self._i_counts

    def _values(self, lo: int, hi: int) -> np.ndarray:
        """Rating values for batch slice [lo, hi) — the shared
        :func:`rating_selection` semantics, chunk-bounded."""
        _, vals = rating_selection(
            self.batch.event[lo:hi], self.batch.target_id[lo:hi],
            (self.batch.float_prop("rating")[lo:hi]
             if self._needs_prop else None), self._fixed)
        return vals.astype(np.float32)

    def _read_filtered_pos(self, side: str, row_pred):
        """Shared chunked streaming over the mmap'd columns: collect the
        rating triples whose mapped ``side`` row index passes
        ``row_pred`` (a vectorized predicate over int64 row indices;
        ``None`` keeps every selected row). ONE loop serves the
        contiguous-range read, the arbitrary-row-set read AND the
        sharded local pass — they must never drift (multihost shard
        equivalence rests on it). Returns ``(pos, rows, cols, vals)``
        with ``pos`` the GLOBAL storage-row positions (this batch's
        local index + ``_pos_base``)."""
        row_lut, col_lut, row_col, col_col = (
            (self._u_lut, self._i_lut, self.batch.entity_id,
             self.batch.target_id) if side == "user" else
            (self._i_lut, self._u_lut, self.batch.target_id,
             self.batch.entity_id))
        pos_out, rows_out, cols_out, vals_out = [], [], [], []
        n = self.batch.n
        for lo in range(0, n, self.chunk):
            hi = min(lo + self.chunk, n)
            m = self._sel[lo:hi].copy()
            if not m.any():
                continue
            r = row_lut[np.asarray(row_col[lo:hi])]
            if row_pred is not None:
                m &= row_pred(r)
                if not m.any():
                    continue
            vals = self._values(lo, hi)
            pos_out.append(np.flatnonzero(m).astype(np.int64)
                           + (lo + self._pos_base))
            rows_out.append(r[m])
            cols_out.append(col_lut[np.asarray(col_col[lo:hi])][m])
            vals_out.append(vals[m])
        if not rows_out:
            z = np.empty(0, np.int64)
            return z, z, z.copy(), np.empty(0, np.float32)
        return (np.concatenate(pos_out), np.concatenate(rows_out),
                np.concatenate(cols_out), np.concatenate(vals_out))

    def _read_filtered(self, side: str, row_pred):
        _, rows, cols, vals = self._read_filtered_pos(side, row_pred)
        return rows, cols, vals

    def read_rows(self, side: str, start: int, stop: int):
        """All rating triples whose ``side`` factor row ∈ [start, stop),
        as (row_idx, col_idx, value) — chunk-bounded temporaries."""
        return self._read_filtered(
            side, lambda r: (r >= start) & (r < stop))

    def read_row_mask(self, side: str, mask: np.ndarray):
        """Rating triples whose ``side`` factor row has ``mask[row]``
        True — the bucketed multihost layout assigns each process a
        NON-contiguous row set (bucket membership is by history
        length), so range reads don't cover it."""
        return self._read_filtered(
            side, lambda r: mask[np.maximum(r, 0)] & (r >= 0))

    def to_coo(self) -> RatingsCOO:
        rows, cols, vals = self.read_rows("user", 0, self.n_users)
        return RatingsCOO(rows.astype(np.int32), cols.astype(np.int32),
                          vals, self.n_users, self.n_items)


class ShardedColumnarRatingsSource(ColumnarRatingsSource):
    """The fully-pushed-down multihost feeding contract (v3): each pod
    host holds ONLY its storage shard of the log
    (``find_columnar(shard=(process_index, process_count))`` — 1/N of
    the bytes off storage), agrees on global factor-row indexation via
    one tiny count-allreduce, and assembles per-factor-row triples
    through a chunked collective shuffle riding the SAME fabric
    training uses (gloo between CPU hosts, ICI/DCN on pods) — the role
    Spark's exchange played behind ``JDBCPEvents.scala:49-89``'s
    partitioned scan. Results are restored to global storage order
    (positions cross the shuffle too), so packing — including
    ``max_history`` truncation, which is order-sensitive — is
    bit-identical to the unsharded read.

    SPMD-collective: every process must construct this source and issue
    the same sequence of reads (``pack_ratings_multihost`` is SPMD by
    construction).
    """

    def __init__(self, shard_batch,
                 event_weights: Optional[Dict[str, Optional[float]]] = None,
                 chunk: int = 4_000_000,
                 exchange_chunk: int = 4_000_000):
        from ..parallel.multihost import allreduce_sum

        super().__init__(shard_batch, event_weights, chunk,
                         count_reduce=allreduce_sum)
        self._pos_base = int(getattr(shard_batch, "shard_offset", 0))
        self.exchange_chunk = exchange_chunk

    def _read_filtered(self, side: str, row_pred):
        from ..parallel.multihost import exchange_filtered

        # local pass: ALL selected triples of MY storage shard (no
        # row_pred — the predicate holds on the RECEIVING side of the
        # shuffle, bounding what each host materializes to its own
        # factor rows plus one in-flight chunk)
        pos, rows, cols, vals = self._read_filtered_pos(side, None)
        pred = row_pred if row_pred is not None \
            else (lambda r: np.ones(len(r), dtype=bool))
        pos, rows, cols, vals = exchange_filtered(
            [pos, rows, cols, vals],
            keep=lambda p, r, c, v: pred(r),
            chunk=self.exchange_chunk)
        order = np.argsort(pos, kind="stable")
        return rows[order], cols[order], vals[order]
