"""Sequential recommendation: causal self-attention over item histories.

A model family BEYOND the reference's inventory (PredictionIO has no
sequence models — SURVEY §5 records sequence parallelism "absent"), made
natural here by the TPU-first substrate: a SASRec-style next-item
predictor — item + position embeddings → a stack of ``num_blocks``
pre-LN causal self-attention blocks (the SAME blockwise-softmax kernel
``ops/ring_attention`` uses; at pod scale the ring path serves sequences
longer than one device holds) → position-wise FFN → tied-embedding item
scores — trained with sampled-softmax cross-entropy under ``jit`` on an
optionally batch-sharded mesh.

Shapes are static everywhere: histories are right-aligned into a fixed
``[N, L]`` window with a padding id, the training step is one compiled
program, and epochs run as a host loop of compiled steps.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ring_attention import ring_attention
from ..parallel.mesh import rows_spec


@dataclass(frozen=True)
class SeqRecParams:
    """Hyperparameters (engine.json-compatible camelCase aliases via the
    controller's param instantiation, like every other algorithm)."""

    dim: int = 48
    heads: int = 2
    num_blocks: int = 1
    max_len: int = 50
    num_epochs: int = 10
    batch_size: int = 128
    learning_rate: float = 1e-3
    n_negatives: int = 64
    dropout: float = 0.0  # reserved; the compiled step is deterministic
    seed: int = 7

    def __post_init__(self):
        if self.dim % self.heads != 0:
            raise ValueError("dim must divide by heads")
        if self.num_blocks < 1:
            raise ValueError("num_blocks must be >= 1 (0 would train an "
                             "attention-free embedding model silently)")


@jax.tree_util.register_dataclass
@dataclass
class SeqRecModel:
    """Learned weights + id indexation. A pytree (weights are children)
    so model persistence's host/device moves reach inside."""

    weights: Dict[str, jax.Array] = field(metadata=dict(static=False))
    n_items: int = field(metadata=dict(static=True))
    item_ids: Optional[object] = field(default=None,
                                       metadata=dict(static=True))
    params: SeqRecParams = field(default_factory=SeqRecParams,
                                 metadata=dict(static=True))
    #: event names the training sequences were built from — serving-time
    #: history reads must filter identically (train/serve skew otherwise)
    events: Optional[Tuple[str, ...]] = field(
        default=None, metadata=dict(static=True))
    #: app the model was trained on — serving-time history reads resolve
    #: against it (the deploy ctx's app_name may be unset; the
    #: e-commerce template does the same)
    app_name: str = field(default="", metadata=dict(static=True))


def sequences_from_ratings(users: np.ndarray, items: np.ndarray,
                           times: np.ndarray, n_users: int,
                           max_len: int) -> np.ndarray:
    """Per-user chronological item sequences, right-aligned into a
    ``[n_users, max_len]`` window padded with -1 (older items beyond the
    window drop — the SASRec convention)."""
    order = np.lexsort((times, users))
    u, it = users[order], items[order]
    out = np.full((n_users, max_len), -1, dtype=np.int32)
    counts = np.bincount(u, minlength=n_users)
    starts = np.zeros(n_users + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    for row in range(n_users):
        s, e = starts[row], starts[row + 1]
        seq = it[s:e][-max_len:]
        if len(seq):
            out[row, -len(seq):] = seq
    return out


def _init_weights(key, n_items: int, p: SeqRecParams) -> Dict[str, jax.Array]:
    ks = jax.random.split(key, 2 + 4 * p.num_blocks)
    d = p.dim
    s = d ** -0.5
    w = {
        # one extra row: the padding id embeds to a learned-but-masked row
        "item_emb": jax.random.normal(ks[0], (n_items + 1, d)) * 0.02,
        "pos_emb": jax.random.normal(ks[1], (p.max_len, d)) * 0.02,
        "lnf": jnp.ones((d,)), "lnfb": jnp.zeros((d,)),
    }
    for blk in range(p.num_blocks):
        o = 2 + 4 * blk
        w.update({
            f"qkv{blk}": jax.random.normal(ks[o], (d, 3 * d)) * s,
            f"attn_out{blk}": jax.random.normal(ks[o + 1], (d, d)) * s,
            f"ff1{blk}": jax.random.normal(ks[o + 2], (d, 4 * d)) * s,
            f"ff2{blk}": (jax.random.normal(ks[o + 3], (4 * d, d))
                          * (4 * d) ** -0.5),
            f"ln1{blk}": jnp.ones((d,)), f"ln1b{blk}": jnp.zeros((d,)),
            f"ln2{blk}": jnp.ones((d,)), f"ln2b{blk}": jnp.zeros((d,)),
        })
    return w


def _layer_norm(x, g, b):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * g + b


def _encode(w: Dict[str, jax.Array], seq: jax.Array, p: SeqRecParams
            ) -> jax.Array:
    """[B, L] padded item ids → [B, L, dim] causal contextual states."""
    B, L = seq.shape
    d, H = p.dim, p.heads
    pad = seq < 0
    ids = jnp.where(pad, p_pad_id(w), seq)
    x = w["item_emb"][ids] + w["pos_emb"][None, -L:]
    x = jnp.where(pad[..., None], 0.0, x)

    for blk in range(p.num_blocks):
        h = _layer_norm(x, w[f"ln1{blk}"], w[f"ln1b{blk}"])
        qkv = h @ w[f"qkv{blk}"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (B, L, H, d // H)
        # the shared attention kernel via its PUBLIC API (ring-capable
        # at pod scale; mesh=None here — L is the history window).
        # key_valid masks the left-pad slots: without it, real
        # positions attend to (learned) pad keys and scores drift with
        # pad count — the classic SASRec padding bug.
        attn = ring_attention(
            q.reshape(shp), k.reshape(shp), v.reshape(shp), mesh=None,
            causal=True, scale=(d // H) ** -0.5,
            key_valid=~pad).reshape(B, L, d)
        x = x + jnp.where(pad[..., None], 0.0,
                          attn @ w[f"attn_out{blk}"])
        h = _layer_norm(x, w[f"ln2{blk}"], w[f"ln2b{blk}"])
        x = x + jnp.where(pad[..., None], 0.0,
                          jax.nn.relu(h @ w[f"ff1{blk}"])
                          @ w[f"ff2{blk}"])
    return _layer_norm(x, w["lnf"], w["lnfb"])


def _compat_model(model: "SeqRecModel") -> "SeqRecModel":
    """Models persisted by the first single-block revision used
    unsuffixed weight keys and a params class without ``num_blocks`` —
    map both forward so old blobs keep serving."""
    w = model.weights
    p = model.params
    changed = False
    if "qkv" in w and "qkv0" not in w:
        ren = {"qkv": "qkv0", "attn_out": "attn_out0", "ff1": "ff10",
               "ff2": "ff20", "ln1": "ln10", "ln1b": "ln1b0",
               "ln2": "ln20", "ln2b": "ln2b0"}
        w = {ren.get(k, k): v for k, v in w.items()}
        changed = True
    if not hasattr(p, "num_blocks"):
        p = SeqRecParams(**{**p.__dict__, "num_blocks": 1})
        changed = True
    if not changed:
        return model
    import dataclasses

    return dataclasses.replace(model, weights=w, params=p)


def p_pad_id(w) -> int:
    return w["item_emb"].shape[0] - 1


@functools.partial(jax.jit, static_argnames=("p", "n_items"),
                   donate_argnums=(0, 1, 2, 3))
def _train_step(w, opt_m, opt_v, step, seq, key, p: SeqRecParams,
                n_items: int):
    """One Adam step of sampled-softmax next-item loss. Inputs [B, L]
    (positions 0..L-2 predict 1..L-1); compiled once per shape. The
    weight/optimizer pytrees and the step counter are donated: every
    caller re-binds them (``w, opt_m, opt_v, step, _ = _train_step(w,
    …)``), so without donation the previous step's buffers stay live
    across the dispatch — 3x the model size in extra peak HBM."""

    def loss_fn(w):
        ctx = _encode(w, seq[:, :-1], p)            # [B, L-1, d]
        targets = seq[:, 1:]                         # [B, L-1]
        valid = (targets >= 0) & (seq[:, :-1] >= 0)
        tgt = jnp.where(valid, targets, 0)
        negs = jax.random.randint(
            key, seq.shape[:1] + (seq.shape[1] - 1, p.n_negatives),
            0, n_items)
        cand = jnp.concatenate([tgt[..., None], negs], axis=-1)
        emb = w["item_emb"][cand]                    # [B, L-1, K+1, d]
        logits = jnp.einsum("bld,blkd->blk", ctx, emb)
        # sampled softmax: positive is slot 0
        ll = jax.nn.log_softmax(logits, axis=-1)[..., 0]
        n = jnp.maximum(valid.sum(), 1)
        return -(jnp.where(valid, ll, 0.0).sum()) / n

    loss, grads = jax.value_and_grad(loss_fn)(w)
    # inline Adam (no optax state-pytree plumbing across shardings)
    b1, b2, eps = 0.9, 0.999, 1e-8
    step = step + 1
    # bias corrections are positive for step >= 1 in exact arithmetic,
    # but step is traced — floor them so a host-side step=0 (restored
    # checkpoint counter) divides by 0.1, not 0.0; v is a sum of
    # squares but bf16 rounding can produce -0-ish values under sqrt
    bc1 = jnp.maximum(1 - b1 ** step, 1e-9)
    bc2 = jnp.maximum(1 - b2 ** step, 1e-9)
    new_w, new_m, new_v = {}, {}, {}
    for kname, g in grads.items():
        m = b1 * opt_m[kname] + (1 - b1) * g
        v = b2 * opt_v[kname] + (1 - b2) * g * g
        mh = m / bc1
        vh = v / bc2
        new_w[kname] = w[kname] - p.learning_rate * mh / (
            jnp.sqrt(jnp.maximum(vh, 0.0)) + eps)
        new_m[kname], new_v[kname] = m, v
    return new_w, new_m, new_v, step, loss


def train_seqrec(sequences: np.ndarray, n_items: int,
                 params: SeqRecParams,
                 mesh: Optional[Mesh] = None,
                 item_ids: Optional[object] = None,
                 events: Optional[Tuple[str, ...]] = None,
                 app_name: str = ""
                 ) -> Tuple[SeqRecModel, List[float]]:
    """Train on ``[N, max_len]`` padded sequences (-1 = pad). Under a
    mesh the BATCH axis shards over all devices (data parallel; XLA
    inserts the gradient all-reduce). Returns (model, per-epoch loss)."""
    seqs = np.asarray(sequences, dtype=np.int32)
    # keep rows with at least one (context, target) pair
    seqs = seqs[(seqs >= 0).sum(axis=1) >= 2]
    if len(seqs) == 0:
        raise ValueError("seqrec needs at least one sequence of length 2")
    key = jax.random.key(params.seed)
    w = _init_weights(key, n_items, params)
    opt_m = {k: jnp.zeros_like(v) for k, v in w.items()}
    opt_v = {k: jnp.zeros_like(v) for k, v in w.items()}
    step = jnp.zeros((), jnp.int32)
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        w = jax.device_put(w, rep)
        opt_m = jax.device_put(opt_m, rep)
        opt_v = jax.device_put(opt_v, rep)

    B = params.batch_size
    n_dev = 1 if mesh is None else mesh.devices.size
    B = max(B // n_dev, 1) * n_dev  # divisible batches for the mesh
    rng = np.random.default_rng(params.seed)
    losses: List[float] = []
    # rows_spec, NOT a hard-coded P(("data","model")): the batch axis
    # shards over whichever mesh is handed in — a (batch, model)
    # serving mesh would KeyError on the literal axis names (caught by
    # the ptpu check sharding rules / audit-hlo, ISSUE 14)
    batch_sharding = None if mesh is None \
        else NamedSharding(mesh, rows_spec(mesh))
    for epoch in range(params.num_epochs):
        order = rng.permutation(len(seqs))
        epoch_losses: list = []
        batches = 0
        for s in range(0, len(seqs) - B + 1, B):
            rows = order[s:s + B]
            batch = seqs[rows]
            xb = jnp.asarray(batch) if batch_sharding is None else \
                jax.device_put(jnp.asarray(batch), batch_sharding)
            key, sub = jax.random.split(key)
            w, opt_m, opt_v, step, loss = _train_step(
                w, opt_m, opt_v, step, xb, sub, params, n_items)
            epoch_losses.append(loss)  # device scalar: no per-step sync
            batches += 1
        if batches == 0:  # fewer rows than one batch: single partial run
            pad_rows = np.resize(np.arange(len(seqs)), B)
            xb = jnp.asarray(seqs[pad_rows])
            if batch_sharding is not None:
                xb = jax.device_put(xb, batch_sharding)
            key, sub = jax.random.split(key)
            w, opt_m, opt_v, step, loss = _train_step(
                w, opt_m, opt_v, step, xb, sub, params, n_items)
            epoch_losses, batches = [loss], 1
        # ONE host sync per epoch (a float() per step would serialize
        # host batch prep against device compute)
        losses.append(float(jnp.mean(jnp.stack(epoch_losses))))
    return SeqRecModel(weights=w, n_items=n_items, item_ids=item_ids,
                       params=params, events=events,
                       app_name=app_name), losses


@functools.partial(jax.jit, static_argnames=("p", "k"))
def _recommend_jit(w, seq, p: SeqRecParams, k: int):
    ctx = _encode(w, seq, p)[:, -1]          # [B, d] last position
    scores = ctx @ w["item_emb"][:-1].T       # exclude the pad row
    return jax.lax.top_k(scores, k)


def recommend_next(model: SeqRecModel, history: Sequence[int], k: int = 10
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k next items for one item-id history (most recent last)."""
    ids, scores = recommend_next_batch(model, [history], k)
    return ids[0], scores[0]


def _pow2_at_least(n: int, cap: int) -> int:
    v = 1
    while v < n:
        v <<= 1
    return min(v, cap)


def recommend_next_batch(model: SeqRecModel,
                         histories: Sequence[Sequence[int]], k: int = 10
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k next items for MANY histories in one device dispatch (the
    batch-predict / serving micro-batcher hot path). Returns
    (ids [B, k], scores [B, k]).

    The compiled kernel runs with batch AND k rounded up to powers of
    two (clamped to the catalog) so arbitrary serving batches reuse
    O(log²) compilations instead of re-tracing per (B, k) pair — the
    same jit-cache-bounding convention as the ALS serving path."""
    model = _compat_model(model)
    p = model.params
    B = len(histories)
    if B > (1 << 16):
        # a silent clamp would IndexError on the fill loop below;
        # callers this large should chunk
        raise ValueError(f"recommend_next_batch: batch of {B} exceeds "
                         f"the {1 << 16} per-dispatch bound; chunk it")
    k_req = min(k, model.n_items)
    B_pad = _pow2_at_least(max(B, 1), 1 << 16)
    k_pad = _pow2_at_least(max(k_req, 1), model.n_items)
    seq = np.full((B_pad, p.max_len), -1, dtype=np.int32)
    for row, history in enumerate(histories):
        h = list(history)[-p.max_len:]
        if h:
            seq[row, -len(h):] = h
    scores, ids = _recommend_jit(model.weights, jnp.asarray(seq), p,
                                 k_pad)
    return (np.asarray(ids)[:B, :k_req],
            np.asarray(scores)[:B, :k_req])
