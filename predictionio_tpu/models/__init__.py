"""TPU algorithm library — the MLlib-role layer."""

from .als import (
    ALSModel,
    ALSParams,
    RatingsCOO,
    recommend_batch,
    recommend_products,
    train_als,
)
from .data import kfold_split, ratings_from_events

__all__ = [
    "ALSModel",
    "ALSParams",
    "RatingsCOO",
    "kfold_split",
    "ratings_from_events",
    "recommend_batch",
    "recommend_products",
    "train_als",
]
