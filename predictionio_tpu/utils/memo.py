"""Thread-safe compute-once memoization.

The first caller of a key runs the thunk; concurrent callers for the
same key block on its Future. Used by the parallel eval sweep's pipeline
prefix caches (``controller/evaluation.py``) and the ALS pack cache
(``models/als.py``) — both would otherwise recompute expensive work in
every worker thread that misses during the first computation's window.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, Tuple


class ComputeOnce:
    """Per-key first-caller-computes cache.

    ``retry_on_failure=True`` drops a failed key so a later caller can
    retry (transient failures — e.g. a device OOM during packing —
    shouldn't poison the cache); waiters of the failing attempt still
    see the exception.
    """

    def __init__(self, retry_on_failure: bool = False):
        self._lock = threading.Lock()
        self._futs: Dict[Hashable, Future] = {}
        self._retry = retry_on_failure

    def get(self, key: Hashable, fn: Callable[[], Any]) -> Any:
        return self.get_timed(key, fn)[0]

    def get_timed(self, key: Hashable, fn: Callable[[], Any]
                  ) -> Tuple[Any, float]:
        """Returns ``(value, seconds_this_caller_spent_computing)`` —
        0.0 for cache hits and for waiters blocked on another thread's
        computation (their blocked time is not their compute time)."""
        with self._lock:
            fut = self._futs.get(key)
            owner = fut is None
            if owner:
                fut = self._futs[key] = Future()
        spent = 0.0
        if owner:
            t0 = time.monotonic()
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — propagate to waiters
                if self._retry:
                    with self._lock:
                        self._futs.pop(key, None)
                fut.set_exception(e)
            spent = time.monotonic() - t0
        return fut.result(), spent
