"""JSON ⇄ typed-object conversion at the REST boundary.

The role of the reference's ``JsonExtractor``
(``workflow/JsonExtractor.scala:39-140``): turn wire JSON into the
template's typed query class and predictions back into wire JSON. The
reference needed dual json4s/gson modes for Scala/Java interop; here
dataclasses (+ numpy/jax scalars) cover the surface.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Optional, Type

import numpy as np


def to_jsonable(obj: Any) -> Any:
    """Render dataclasses / numpy / jax values as JSON-compatible data."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if hasattr(obj, "to_json"):  # custom wire format wins over dataclass
        return obj.to_json()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, Mapping):
        return {str(k): to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [to_jsonable(x) for x in obj]
    if isinstance(obj, np.generic):
        # ptpu: allow[host-sync-in-hot-path] — numpy scalar, host-side
        return obj.item()
    if isinstance(obj, np.ndarray):
        # ptpu: allow[host-sync-in-hot-path] — numpy array, host-side
        return obj.tolist()
    if hasattr(obj, "tolist"):  # jax.Array without importing jax here
        # ptpu: allow[host-sync-in-hot-path] — THE serialization
        # boundary: results must land on host exactly here, after the
        # serve/readback span, to become wire JSON (the one blessed
        # D2H funnel of the query path, like ragged._host for packing)
        return obj.tolist()
    return str(obj)


def from_jsonable(cls: Optional[Type], obj: Any) -> Any:
    """Parse wire JSON into ``cls`` when it is a dataclass; pass through
    otherwise. Unknown keys are rejected (mirrors the reference's strict
    query mapping, which 400s on mismatch)."""
    if cls is None or not dataclasses.is_dataclass(cls):
        return obj
    if not isinstance(obj, Mapping):
        raise ValueError(f"expected JSON object for {cls.__name__}, "
                         f"got {type(obj).__name__}")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    # declared wire aliases, e.g. ALSParams.reg carries
    # metadata={"aliases": ("lambda",)} for engine.json compatibility
    aliases = {a: f.name for f in dataclasses.fields(cls)
               for a in f.metadata.get("aliases", ())}
    # the reference's wire format is camelCase (e.g. whiteList) while the
    # dataclasses are snake_case; accept both spellings on input
    normalized = {}
    for key, value in obj.items():
        name = key if key in fields else _snake_case(key)
        if name in aliases:
            name = aliases[name]
        if name not in fields and f"{name}_" in fields:
            name = f"{name}_"  # python-keyword fields, e.g. lambda → lambda_
        if name not in fields:
            raise ValueError(f"unknown field(s) for {cls.__name__}: "
                             f"[{key!r}]")
        if name in normalized:
            raise ValueError(f"duplicate field for {cls.__name__}: {key!r}")
        normalized[name] = value
    kwargs = {}
    for name, value in normalized.items():
        ftype = _dataclass_type(fields[name].type, cls)
        kwargs[name] = (from_jsonable(ftype, value)
                        if ftype is not None else value)
    return cls(**kwargs)


def _snake_case(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _dataclass_type(annotation: Any, owner: Type) -> Optional[Type]:
    """Resolve a field annotation to a dataclass type (handles string
    annotations and Optional[X]); None when the field isn't one."""
    import sys
    import typing

    if isinstance(annotation, str):
        mod = sys.modules.get(owner.__module__)
        try:
            annotation = eval(annotation, vars(mod) if mod else {})  # noqa: S307
        except Exception:
            return None
    origin = typing.get_origin(annotation)
    if origin is typing.Union:
        args = [a for a in typing.get_args(annotation) if a is not type(None)]
        if len(args) == 1:
            annotation = args[0]
    if isinstance(annotation, type) and dataclasses.is_dataclass(annotation):
        return annotation
    return None
