"""Host/device platform helpers shared by benchmarks and tools."""

from __future__ import annotations

import os


_cache_enabled = False


def enable_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at a stable on-disk dir.

    The reference pays JVM/Spark startup once per ``pio`` command; our
    analogue is XLA compilation — and through a remote-compile tunnel a
    single ALS train program costs ~20-40s to build. The cache is keyed
    by HLO fingerprint, so every CLI stage (train, eval, deploy) and
    every repeated run reuses compiled programs across *processes*
    (measured: 2.7s → 0.6s for a toy jit; ~40s → ~0s for the ML-20M
    train step). Default location: ``$PIO_COMPILE_CACHE``, else
    ``$PIO_HOME/compile_cache``, else ``~/.cache/predictionio_tpu/xla``.
    Set ``PIO_COMPILE_CACHE=off`` to disable. Safe to call many times;
    first call wins. Call after ``import jax`` and before first use.
    """
    global _cache_enabled
    if _cache_enabled:
        return
    loc = os.environ.get("PIO_COMPILE_CACHE", "")
    if loc.lower() in ("off", "0", "none", "disabled"):
        return
    # CPU compiles are fast and XLA:CPU AOT executables embed host
    # machine features (observed: a cached +prefer-no-gather binary
    # warns/risks SIGILL on a host without it) — the cache only pays
    # on accelerator backends, where a program costs 20-40s through a
    # remote-compile tunnel. Check the RESOLVED backend, not just the
    # env var: a host with no accelerator auto-selects CPU with the
    # env unset. (Callers reach here right before device use, so the
    # backend init this forces is work they were about to do anyway.)
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return
    try:
        import jax

        if jax.default_backend() == "cpu":
            return
    except Exception:  # noqa: BLE001 — backend probe failed: no cache
        return
    if not loc:
        home = os.environ.get("PIO_HOME", "")
        loc = (os.path.join(home, "compile_cache") if home else
               os.path.join(os.environ.get("XDG_CACHE_HOME",
                                           os.path.expanduser("~/.cache")),
                            "predictionio_tpu", "xla"))
    try:
        os.makedirs(loc, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", loc)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _cache_enabled = True
    except Exception:  # noqa: BLE001 — cache is an accelerator, never a dep
        pass


def force_cpu_if_requested() -> None:
    """Make ``JAX_PLATFORMS=cpu`` authoritative.

    The env var alone does not stop an installed TPU PJRT plugin from
    initializing — and through a device tunnel that init can HANG
    indefinitely when the tunnel is down (exactly how round 2's driver
    bench died). The config update is authoritative; call this after
    importing jax and before the first device use.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
