"""Host/device platform helpers shared by benchmarks and tools."""

from __future__ import annotations

import os


def force_cpu_if_requested() -> None:
    """Make ``JAX_PLATFORMS=cpu`` authoritative.

    The env var alone does not stop an installed TPU PJRT plugin from
    initializing — and through a device tunnel that init can HANG
    indefinitely when the tunnel is down (exactly how round 2's driver
    bench died). The config update is authoritative; call this after
    importing jax and before the first device use.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
