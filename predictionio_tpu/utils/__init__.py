"""Host-side utilities."""

from .jsonutil import from_jsonable, to_jsonable  # noqa: F401
