"""Tracing/profiling — a first-class improvement over the reference.

The reference has no tracing at all (SURVEY §5: observability = logs +
the external Spark UI). Here the XLA profiler is wired into the
workflow: ``trace(dir)`` captures a device trace viewable in
TensorBoard/XProf/Perfetto, ``annotate(name)`` labels host-side phases so
they show up on the trace timeline, and ``timed(name)`` collects
wall-clock spans into an in-process registry the servers can expose.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, Iterator, Optional

from ..obs.histogram import StreamingHistogram

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA device trace under ``log_dir`` (no-op when None).

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("XLA trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label a host-side phase on the profiler timeline."""
    try:
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield
    except ImportError:  # profiling must never break the workflow
        yield


class SpanRegistry:
    """Thread-safe wall-clock span collection, bounded per name.

    Round-1 kept a raw ``List[float]`` per span — unbounded memory on a
    long-lived server. Each name is now one fixed-bucket
    :class:`~predictionio_tpu.obs.histogram.StreamingHistogram`:
    ``record`` is O(1), memory is constant however many observations
    arrive, and :meth:`summary` gains p50/p90/p99 while keeping the
    original ``count/total_sec/mean_sec/max_sec`` keys.
    """

    #: a runaway caller generating span names per request must not grow
    #: the registry without bound; past this, records fold into one
    #: overflow bucket (visible, not silent)
    MAX_SPAN_NAMES = 1024
    _OVERFLOW = "(overflow)"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: Dict[str, StreamingHistogram] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            hist = self._spans.get(name)
            if hist is None:
                if len(self._spans) >= self.MAX_SPAN_NAMES:
                    name = self._OVERFLOW
                    hist = self._spans.get(name)
                if hist is None:
                    hist = self._spans[name] = StreamingHistogram()
        hist.record(seconds)

    def histograms(self) -> Dict[str, StreamingHistogram]:
        """Live per-name histograms (the /metrics exposition bridge)."""
        with self._lock:
            return dict(self._spans)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, h in self.histograms().items():
            if not h.count:
                continue
            s = h.snapshot()
            out[name] = {
                "count": s["count"],
                "total_sec": s["sum"],
                "mean_sec": s["mean"],
                "max_sec": s["max"],
                "p50": s["p50"],
                "p90": s["p90"],
                "p99": s["p99"],
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


#: Process-wide registry; the engine server's status page reads it.
spans = SpanRegistry()


@contextlib.contextmanager
def timed(name: str,
          registry: Optional[SpanRegistry] = None) -> Iterator[None]:
    """Time a block into the span registry AND the profiler timeline."""
    reg = registry if registry is not None else spans
    t0 = time.monotonic()
    with annotate(name):
        try:
            yield
        finally:
            reg.record(name, time.monotonic() - t0)
