"""Tracing/profiling — a first-class improvement over the reference.

The reference has no tracing at all (SURVEY §5: observability = logs +
the external Spark UI). Here the XLA profiler is wired into the
workflow: ``trace(dir)`` captures a device trace viewable in
TensorBoard/XProf/Perfetto, ``annotate(name)`` labels host-side phases so
they show up on the trace timeline, and ``timed(name)`` collects
wall-clock spans into an in-process registry the servers can expose.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA device trace under ``log_dir`` (no-op when None).

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("XLA trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label a host-side phase on the profiler timeline."""
    try:
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield
    except ImportError:  # profiling must never break the workflow
        yield


class SpanRegistry:
    """Thread-safe wall-clock span collection (count/total/max per name)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: Dict[str, List[float]] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._spans.setdefault(name, []).append(seconds)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": len(xs),
                    "total_sec": sum(xs),
                    "mean_sec": sum(xs) / len(xs),
                    "max_sec": max(xs),
                }
                for name, xs in self._spans.items() if xs
            }

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


#: Process-wide registry; the engine server's status page reads it.
spans = SpanRegistry()


@contextlib.contextmanager
def timed(name: str,
          registry: Optional[SpanRegistry] = None) -> Iterator[None]:
    """Time a block into the span registry AND the profiler timeline."""
    reg = registry if registry is not None else spans
    t0 = time.monotonic()
    with annotate(name):
        try:
            yield
        finally:
            reg.record(name, time.monotonic() - t0)
