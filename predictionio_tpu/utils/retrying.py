"""Bounded-exponential-backoff retry — the ONE retry policy shared by
the remote-storage client, the stream trainer's storage calls, and any
other code that talks to something transiently failable.

Every loop here is *bounded* (max attempts) and *paced* (exponential
backoff with a cap and optional jitter) — the two properties ``ptpu
check``'s ``unbounded-retry`` rule enforces on server/streaming/storage
code (docs/static-analysis.md). Transient faults degrade into a short
stall; persistent ones surface the LAST error after a known, finite
budget instead of wedging a daemon.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Tuple, Type

__all__ = ["RetryPolicy", "backoff_delays", "retry_call"]


@dataclass(frozen=True)
class RetryPolicy:
    """``attempt k`` (0-based) waits ``min(cap_ms, base_ms * 2**k)``
    ± ``jitter`` fraction before retrying."""

    max_attempts: int = 4      # total tries, including the first
    base_ms: float = 50.0
    cap_ms: float = 2000.0
    jitter: float = 0.1        # fraction of the delay, uniform ±
    #: seeded RNG for reproducible schedules in tests/drills; None =
    #: process randomness
    seed: Optional[int] = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")


def backoff_delays(policy: RetryPolicy) -> Iterator[float]:
    """The seconds to sleep before retry k (yields
    ``max_attempts - 1`` values — no sleep after the last failure)."""
    rng = random.Random(policy.seed) if policy.seed is not None \
        else random
    for k in range(policy.max_attempts - 1):
        delay = min(policy.cap_ms, policy.base_ms * (2 ** k)) / 1000.0
        if policy.jitter:
            delay *= 1.0 + rng.uniform(-policy.jitter, policy.jitter)
        yield max(delay, 0.0)


def retry_call(fn: Callable, *args,
               policy: RetryPolicy = RetryPolicy(),
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception, back
    off per ``policy`` and retry, re-raising the last error once the
    attempt budget is spent. ``on_retry(attempt, exc)`` observes each
    failure (telemetry/logging) before the sleep."""
    delays = backoff_delays(policy)
    for attempt in range(policy.max_attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if on_retry is not None:
                on_retry(attempt, e)
            try:
                delay = next(delays)
            except StopIteration:
                raise e
            time.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
