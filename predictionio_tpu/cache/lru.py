"""Sharded LRU + TTL cache with tag-based invalidation.

The storage primitive under the serving cache hierarchy (ISSUE 4):
``shards`` independent ``OrderedDict``s, each behind its own lock, so
concurrent HTTP worker threads don't serialize on one mutex. Every
entry carries a TTL (the staleness *bound* — the invalidation bus
usually clears entries long before it expires) and an optional set of
**tags**; :meth:`invalidate_tag` removes every entry carrying a tag in
O(entries-with-that-tag), which is how one ingested event for entity
``u42`` kills exactly the cached results that depended on ``u42``.

Keys are ``(namespace, payload)`` tuples by convention: the engine
server namespaces the query tier by engine-instance id (release arm),
so :meth:`flush` with a namespace wipes one arm without touching the
other.
"""

from __future__ import annotations

import sys
import time
from collections import OrderedDict

from ..concurrency import new_lock
from typing import Any, Dict, Hashable, Iterable, Optional, Tuple

__all__ = ["ShardedTTLCache", "approx_bytes"]


def approx_bytes(value: Any, _depth: int = 0) -> int:
    """Cheap recursive size estimate for cache byte accounting — close
    enough for capacity planning, never exact (depth-capped so a
    pathological nest can't turn a ``put`` into a traversal)."""
    n = sys.getsizeof(value, 64)
    if _depth >= 3:
        return n
    if isinstance(value, dict):
        for k, v in value.items():
            n += approx_bytes(k, _depth + 1) + approx_bytes(v, _depth + 1)
    elif isinstance(value, (list, tuple, set, frozenset)):
        for v in value:
            n += approx_bytes(v, _depth + 1)
    return n


class _Shard:
    __slots__ = ("lock", "entries", "tags", "bytes")

    def __init__(self) -> None:
        self.lock = new_lock("ShardedTTLCache.shard.lock")
        #: key → (value, expires_at, tags, cost_bytes); insertion order
        #: is recency order (move_to_end on hit)
        self.entries: "OrderedDict[Hashable, Tuple]" = OrderedDict()
        #: tag → set of keys carrying it
        self.tags: Dict[str, set] = {}
        self.bytes = 0


class ShardedTTLCache:
    """Thread-safe LRU+TTL map with tags and namespace flush."""

    def __init__(self, max_entries: int = 8192, ttl_sec: float = 30.0,
                 shards: int = 8,
                 clock=time.monotonic) -> None:
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = max_entries
        self.ttl_sec = float(ttl_sec)
        self._clock = clock
        self._shards = [_Shard() for _ in range(max(shards, 1))]
        #: per-shard capacity; ceil so shards*cap >= max_entries
        self._shard_cap = max(
            1, -(-max_entries // len(self._shards)))
        self._stats_lock = new_lock("ShardedTTLCache._stats_lock")
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._invalidations = 0
        self._expirations = 0

    def _shard(self, key: Hashable) -> _Shard:
        return self._shards[hash(key) % len(self._shards)]

    def _drop_locked(self, shard: _Shard, key: Hashable) -> None:
        value, exp, tags, cost = shard.entries.pop(key)
        shard.bytes -= cost
        for t in tags:
            keys = shard.tags.get(t)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del shard.tags[t]

    # -- read/write ---------------------------------------------------------
    def lookup(self, key: Hashable) -> Tuple[bool, Any]:
        """``(True, value)`` on a live hit, ``(False, None)`` otherwise
        (expired entries are dropped lazily here)."""
        shard = self._shard(key)
        with shard.lock:
            entry = shard.entries.get(key)
            if entry is not None:
                if self._clock() < entry[1]:
                    shard.entries.move_to_end(key)
                    with self._stats_lock:
                        self._hits += 1
                    return True, entry[0]
                self._drop_locked(shard, key)
                with self._stats_lock:
                    self._expirations += 1
        with self._stats_lock:
            self._misses += 1
        return False, None

    def put(self, key: Hashable, value: Any,
            tags: Iterable[str] = (),
            cost_bytes: Optional[int] = None,
            ttl_sec: Optional[float] = None) -> None:
        cost = approx_bytes(value) if cost_bytes is None else cost_bytes
        tags = tuple(tags)
        expires = self._clock() + (self.ttl_sec if ttl_sec is None
                                   else ttl_sec)
        shard = self._shard(key)
        evicted = 0
        with shard.lock:
            if key in shard.entries:
                self._drop_locked(shard, key)
            shard.entries[key] = (value, expires, tags, cost)
            shard.bytes += cost
            for t in tags:
                shard.tags.setdefault(t, set()).add(key)
            while len(shard.entries) > self._shard_cap:
                oldest = next(iter(shard.entries))
                self._drop_locked(shard, oldest)
                evicted += 1
        if evicted:
            with self._stats_lock:
                self._evictions += evicted

    # -- invalidation -------------------------------------------------------
    def invalidate_tag(self, tag: str) -> int:
        """Remove every entry tagged ``tag``; returns how many died."""
        removed = 0
        for shard in self._shards:
            with shard.lock:
                keys = shard.tags.pop(tag, None)
                if not keys:
                    continue
                for key in list(keys):
                    if key in shard.entries:
                        self._drop_locked(shard, key)
                        removed += 1
        if removed:
            with self._stats_lock:
                self._invalidations += removed
        return removed

    def invalidate_key(self, key: Hashable) -> bool:
        shard = self._shard(key)
        with shard.lock:
            if key in shard.entries:
                self._drop_locked(shard, key)
                removed = True
            else:
                removed = False
        if removed:
            with self._stats_lock:
                self._invalidations += 1
        return removed

    def flush(self, namespace: Optional[Any] = None) -> int:
        """Drop everything (``namespace=None``) or only the entries
        whose tuple key starts with ``namespace``."""
        removed = 0
        for shard in self._shards:
            with shard.lock:
                if namespace is None:
                    removed += len(shard.entries)
                    shard.entries.clear()
                    shard.tags.clear()
                    shard.bytes = 0
                else:
                    doomed = [k for k in shard.entries
                              if isinstance(k, tuple) and k
                              and k[0] == namespace]
                    for k in doomed:
                        self._drop_locked(shard, k)
                    removed += len(doomed)
        if removed:
            with self._stats_lock:
                self._invalidations += removed
        return removed

    # -- observability ------------------------------------------------------
    def __len__(self) -> int:
        return sum(len(s.entries) for s in self._shards)

    @property
    def bytes(self) -> int:
        return sum(s.bytes for s in self._shards)

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            hits, misses = self._hits, self._misses
            out = {
                "entries": len(self),
                "bytes": self.bytes,
                "maxEntries": self.max_entries,
                "ttlSec": self.ttl_sec,
                "hits": hits,
                "misses": misses,
                "evictions": self._evictions,
                "invalidations": self._invalidations,
                "expirations": self._expirations,
            }
        total = hits + misses
        out["hitRatio"] = (hits / total) if total else 0.0
        return out
