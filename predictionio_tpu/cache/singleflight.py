"""Singleflight: concurrent identical cache misses compute ONCE.

Unlike :class:`~predictionio_tpu.utils.memo.ComputeOnce` (a permanent
memo), a singleflight entry lives only while the computation is in
flight: the first caller for a key becomes the **leader** and runs the
thunk; callers that arrive before it finishes block on the same Future
and share the result (or the exception); the entry is then removed, so
the next miss after the cache expires/invalidates computes fresh.

This is what keeps a hot-key TTL expiry from turning into a thundering
herd of identical device dispatches: N concurrent misses for one query
cost one supplement + one dispatch, not N.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, Tuple

from ..concurrency import new_lock

__all__ = ["SingleFlight"]


class SingleFlight:
    def __init__(self) -> None:
        self._lock = new_lock("SingleFlight._lock")
        self._flights: Dict[Hashable, Future] = {}
        self._coalesced = 0  # followers served by a leader's flight

    def do(self, key: Hashable, fn: Callable[[], Any]
           ) -> Tuple[Any, bool]:
        """Returns ``(value, leader)`` — ``leader`` is True for the
        caller that actually ran ``fn``. Exceptions propagate to the
        leader AND every follower of that flight."""
        with self._lock:
            fut = self._flights.get(key)
            leader = fut is None
            if leader:
                fut = self._flights[key] = Future()
            else:
                self._coalesced += 1
        if leader:
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — to all waiters
                fut.set_exception(e)
            finally:
                with self._lock:
                    self._flights.pop(key, None)
        return fut.result(), leader

    @property
    def coalesced(self) -> int:
        """How many callers were deduplicated onto another's flight."""
        with self._lock:
            return self._coalesced

    def in_flight(self) -> int:
        with self._lock:
            return len(self._flights)
