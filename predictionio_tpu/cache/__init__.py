"""Serving-path cache hierarchy (ISSUE 4).

Three tiers between HTTP parse and device dispatch — an exact-key
query-result cache (sharded LRU+TTL with singleflight), a feature/
supplement cache for serving-time event-store reads, and a
device-resident hot-entity tier — kept honest by an invalidation bus
the event server publishes to on every ingest. See
docs/serving-cache.md for semantics and tuning.

Pure host-side code: importing this package never touches jax (the
event server and storage-only CLI commands import it).
"""

from .bus import InvalidationBus, default_bus
from .hierarchy import ServingCache, canonical_key, entity_tag
from .hot import HotEntityTier
from .lru import ShardedTTLCache, approx_bytes
from .singleflight import SingleFlight

__all__ = [
    "HotEntityTier",
    "InvalidationBus",
    "ServingCache",
    "ShardedTTLCache",
    "SingleFlight",
    "approx_bytes",
    "canonical_key",
    "default_bus",
    "entity_tag",
]
