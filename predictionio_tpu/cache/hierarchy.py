"""The serving cache hierarchy: query tier + feature tier + hot tier.

One :class:`ServingCache` per :class:`~..server.engineserver.QueryServer`
(ISSUE 4). The tiers, in the order a query meets them:

1. **query** — exact-key result cache consulted before the
   micro-batcher: a hot query returns its JSON straight from memory,
   skipping parse→supplement→dispatch→serve entirely. Keys are
   ``(namespace, canonical-query-JSON)``; the namespace is the serving
   engine-instance id, so the stable and candidate release arms can
   never serve each other's results, and a rebind flushes per-arm.
2. **feature** — serving-time event-store reads (the e-commerce
   template's seen/unavailable/weighted/recent lookups) cached under a
   shorter TTL and invalidated per-entity by the bus.
3. **hot** — the device-resident pinned-row tier
   (:class:`~.hot.HotEntityTier`), refreshed from the query tier's hit
   traffic.

Entries carry entity **tags** (``"user:u42"``,
``"constraint:weightedItems"``); the invalidation bus maps one
ingested event to exactly the tagged entries it contradicts. A
``constraint`` entity ``$set`` (catalog-wide blacklist/weights) flushes
the whole query tier — every cached result may now be wrong.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, Optional, Tuple

from ..concurrency import new_lock
from .bus import InvalidationBus, default_bus
from .hot import HotEntityTier, PinFn
from .lru import ShardedTTLCache
from .singleflight import SingleFlight

__all__ = ["ServingCache", "canonical_key", "entity_tag"]


def canonical_key(query_json: Any) -> str:
    """Stable exact-match key for a query payload: key order must not
    matter (two clients sending the same query differently ordered are
    the same query)."""
    try:
        return json.dumps(query_json, sort_keys=True,
                          separators=(",", ":"))
    except (TypeError, ValueError):
        return repr(query_json)


def entity_tag(entity_type: str, entity_id: Any) -> str:
    return f"{entity_type}:{entity_id}"


class ServingCache:
    """Tier container + bus subscription + ``pio_cache_*`` metrics."""

    def __init__(self, *,
                 query_entries: int = 8192,
                 query_ttl_sec: float = 30.0,
                 feature_entries: int = 8192,
                 feature_ttl_sec: float = 5.0,
                 hot_capacity: int = 512,
                 hot_refresh_every: int = 256,
                 pin_fn: Optional[PinFn] = None,
                 bus: Optional[InvalidationBus] = None) -> None:
        self.query = ShardedTTLCache(max_entries=query_entries,
                                     ttl_sec=query_ttl_sec)
        self.features = ShardedTTLCache(max_entries=feature_entries,
                                        ttl_sec=feature_ttl_sec)
        self.hot = (HotEntityTier(pin_fn, capacity=hot_capacity,
                                  refresh_every=hot_refresh_every)
                    if pin_fn is not None and hot_capacity > 0 else None)
        self.flight = SingleFlight()
        #: guards the flat counters below — bus deliveries arrive on
        #: whatever thread accepted the ingest, so even `x += 1` is a
        #: read-modify-write race without it
        self._counter_lock = new_lock("ServingCache._counter_lock")
        self._flushes = 0
        self._bus_events = 0
        # invalidation epochs: a query computed CONCURRENTLY with an
        # ingest must not be cached after the ingest's invalidation
        # already ran (it would then serve stale until the TTL). Every
        # invalidation bumps the entity tag's epoch (flushes bump the
        # global one) BEFORE removing entries; fill paths snapshot the
        # epoch pre-compute and drop their put if it moved (see
        # put_query_fresh).
        self._epoch_lock = new_lock("ServingCache._epoch_lock")
        self._global_epoch = 0
        self._tag_epochs: Dict[str, int] = {}
        self._stale_put_drops = 0
        self.bus = bus if bus is not None else default_bus()
        # weak subscription: dropping the owning QueryServer drops us
        self.bus.subscribe(self)

    # -- invalidation epochs -------------------------------------------------
    #: tag-epoch map cap — past it the map is cleared and the GLOBAL
    #: epoch bumped instead (every in-flight put aborts once; correct,
    #: just momentarily conservative)
    MAX_TAG_EPOCHS = 65536

    def epoch_token(self, tag: Optional[str]):
        """Snapshot taken BEFORE computing a cacheable result."""
        with self._epoch_lock:
            return (self._global_epoch,
                    self._tag_epochs.get(tag, 0) if tag else 0, tag)

    def _bump_tag(self, tag: str) -> None:
        with self._epoch_lock:
            if len(self._tag_epochs) >= self.MAX_TAG_EPOCHS:
                self._tag_epochs.clear()
                self._global_epoch += 1
            self._tag_epochs[tag] = self._tag_epochs.get(tag, 0) + 1

    def _bump_global(self) -> None:
        with self._epoch_lock:
            self._global_epoch += 1

    def _epoch_moved(self, token) -> bool:
        g, te, tag = token
        with self._epoch_lock:
            return (self._global_epoch != g
                    or (tag is not None
                        and self._tag_epochs.get(tag, 0) != te))

    def put_query_fresh(self, key, value, tags: Tuple[str, ...],
                        token) -> bool:
        """Cache a computed result UNLESS an invalidation covering it
        ran since ``token`` was taken. Order matters: put FIRST, then
        re-check — an invalidator that runs after the put finds the
        entry in the tag index and removes it itself; one that ran
        entirely before the put is caught by the re-check. Either way
        no stale entry survives to the TTL."""
        if self._epoch_moved(token):
            self._stale_put_drops += 1
            return False
        self.query.put(key, value, tags=tags)
        if self._epoch_moved(token):
            self.query.invalidate_key(key)
            self._stale_put_drops += 1
            return False
        return True

    # -- invalidation (the bus calls this on every ingest) ------------------
    def on_event(self, app_id: Optional[int], entity_type: str,
                 entity_id: str, event_name: str = "") -> None:
        with self._counter_lock:
            self._bus_events += 1
        tag = entity_tag(entity_type, entity_id)
        self._bump_tag(tag)  # BEFORE removal: in-flight fills must see
        self.query.invalidate_tag(tag)          # the moved epoch
        self.features.invalidate_tag(tag)
        if entity_type == "constraint":
            # catalog-wide constraints (unavailableItems, weightedItems)
            # re-shape EVERY result — per-tag surgery can't be precise
            self._bump_global()
            self.query.flush()

    def invalidate_entities(self, entity_type: str, entity_ids) -> None:
        """Per-entity invalidation OUTSIDE the ingest bus: the
        streaming trainer's delta apply (ISSUE 10) calls this after
        hot-swapping folded factor rows — a result for a touched
        entity cached between its ingest (which the bus already
        invalidated) and the fold-in was computed by the pre-fold
        model and must not survive to the TTL. Same epoch discipline
        as :meth:`on_event`: bump BEFORE removal so in-flight fills
        drop themselves."""
        for eid in entity_ids:
            tag = entity_tag(entity_type, eid)
            self._bump_tag(tag)
            self.query.invalidate_tag(tag)
            self.features.invalidate_tag(tag)

    # -- flush (rebind / operator) ------------------------------------------
    def flush_namespace(self, namespace: str) -> int:
        """Wipe one release arm's query results (promote/rollback of
        the OTHER arm leaves this one untouched)."""
        self._bump_global()
        return self.query.flush(namespace)

    def flush_all(self) -> Dict[str, int]:
        """Full flush — every rebind (deploy/reload/promote/rollback)
        and the ``/cache/flush`` operator route take this path: a new
        model must never serve results computed by the old one."""
        with self._counter_lock:
            self._flushes += 1
        self._bump_global()
        out = {"query": self.query.flush(),
               "feature": self.features.flush()}
        if self.hot is not None:
            out["hot"] = self.hot.flush()
        return out

    # -- observability ------------------------------------------------------
    def _tiers(self) -> Iterable[Tuple[str, Any]]:
        yield "query", self.query
        yield "feature", self.features
        if self.hot is not None:
            yield "hot", self.hot

    def stats(self) -> Dict[str, Any]:
        with self._counter_lock:
            flushes, bus_events = self._flushes, self._bus_events
        out: Dict[str, Any] = {"enabled": True,
                               "flushes": flushes,
                               "busEvents": bus_events,
                               "singleflightCoalesced":
                                   self.flight.coalesced,
                               "stalePutDrops": self._stale_put_drops,
                               "tiers": {}}
        for name, tier in self._tiers():
            out["tiers"][name] = tier.stats()
        return out

    def register_metrics(self, registry) -> None:
        """Mount the ``pio_cache_*`` series on a server's
        :class:`~predictionio_tpu.obs.MetricsRegistry`. Gauges backed
        by live tier counters — one source of truth, no dual
        bookkeeping (the counters only go up, so ``rate()`` works)."""
        fams = {
            "hits": registry.gauge(
                "pio_cache_hits",
                "Serving-cache hits per tier (monotonic)"),
            "misses": registry.gauge(
                "pio_cache_misses",
                "Serving-cache misses per tier (monotonic)"),
            "evictions": registry.gauge(
                "pio_cache_evictions",
                "Entries evicted by LRU capacity per tier (monotonic)"),
            "invalidations": registry.gauge(
                "pio_cache_invalidations",
                "Entries removed by bus/TTL-flush invalidation per "
                "tier (monotonic)"),
            "entries": registry.gauge(
                "pio_cache_entries", "Live cached entries per tier"),
            "bytes": registry.gauge(
                "pio_cache_bytes",
                "Approximate bytes held per tier"),
            "hitRatio": registry.gauge(
                "pio_cache_hit_ratio",
                "Lifetime hit ratio per tier"),
        }
        for name, tier in self._tiers():
            for stat, fam in fams.items():
                fam.labels(tier=name).set_fn(
                    lambda t=tier, s=stat: t.stats()[s])
        registry.gauge(
            "pio_cache_singleflight_coalesced",
            "Concurrent identical misses deduplicated onto one "
            "computation (monotonic)",
            fn=lambda: self.flight.coalesced)
        registry.gauge(
            "pio_cache_flushes",
            "Full cache flushes (rebind or operator, monotonic)",
            # ptpu: guarded-by[_counter_lock] — scrape-time snapshot of
            # a monotonic int; a torn read is impossible in CPython and
            # an off-by-one scrape is harmless
            fn=lambda: self._flushes)
