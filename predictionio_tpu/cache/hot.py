"""Device-resident hot-entity tier: pin the Zipf head on the device.

Production recommendation traffic is heavily skewed — a few percent of
users produce most queries (the hot-entity skew Google's ads serving
and ALX both exploit; PAPERS.md arxiv 2501.10546 / 2112.02194). This
tier counts per-entity serve traffic and periodically **pins** the
top-K hottest entities through a caller-supplied ``pin_fn`` — for the
ALS templates that means gathering those users' factor rows into one
small device-resident ``[K, rank]`` table
(:meth:`~predictionio_tpu.templates.recommendation.ALSAlgorithm.pin_hot_entities`),
so a known-hot user's query skips the host-side row gather + transfer
and dispatches straight off HBM.

The tier never blocks serving: ``record``/``lookup`` are dict lookups;
the refresh (hit-stat ranking + device transfer) runs on a background
thread, and the pinned map is swapped atomically. ``flush()`` (called
on every rebind — promote/rollback/reload) drops pins AND hit stats so
a new model never serves rows pinned from the old one.
"""

from __future__ import annotations

import logging
import threading

from ..concurrency import new_lock
from typing import Any, Callable, Dict, Optional, Tuple

log = logging.getLogger(__name__)

__all__ = ["HotEntityTier"]

#: pin_fn signature: (entity_keys) -> ({entity: handle}, pinned_bytes)
PinFn = Callable[[list], Tuple[Dict[str, Any], int]]


class HotEntityTier:
    def __init__(self, pin_fn: PinFn, capacity: int = 512,
                 refresh_every: int = 256) -> None:
        self.pin_fn = pin_fn
        self.capacity = max(capacity, 1)
        self.refresh_every = max(refresh_every, 1)
        self._lock = new_lock("HotEntityTier._lock")
        self._counts: Dict[str, int] = {}
        self._pinned: Dict[str, Any] = {}
        self._bytes = 0
        self._records = 0
        self._hits = 0
        self._misses = 0
        self._refreshes = 0
        self._generation = 0  # bumped by flush(); stale refreshes drop
        self._refreshing = False
        self._refresh_done: Optional[threading.Event] = None

    # -- hot path -----------------------------------------------------------
    def record(self, key: str) -> None:
        """Count one serve for ``key``; every ``refresh_every`` records
        a background re-pin is scheduled."""
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._records += 1
            due = self._records % self.refresh_every == 0
            # bound the stat map: keep the head, drop the long tail
            if len(self._counts) > 8 * self.capacity:
                keep = sorted(self._counts.items(),
                              key=lambda kv: kv[1],
                              reverse=True)[:2 * self.capacity]
                self._counts = dict(keep)
        if due:
            self.refresh(wait=False)

    def lookup(self, key: str) -> Optional[Any]:
        """The pinned handle for ``key``, or None (counts hit/miss)."""
        with self._lock:
            handle = self._pinned.get(key)
            if handle is not None:
                self._hits += 1
            else:
                self._misses += 1
        return handle

    # -- refresh ------------------------------------------------------------
    def refresh(self, wait: bool = True) -> None:
        """Re-rank the hit stats and re-pin the top-K. ``wait=False``
        runs it on a daemon thread (the serving-path mode); at most one
        refresh runs at a time — ``wait=True`` against an in-flight
        refresh blocks until THAT one lands instead of skipping."""
        start = False
        with self._lock:
            if not self._refreshing:
                self._refreshing = True
                self._refresh_done = threading.Event()
                start = True
            done = self._refresh_done
        if start:
            if wait:
                self._refresh_now()
            else:
                threading.Thread(target=self._refresh_now, daemon=True,
                                 name="hot-tier-refresh").start()
        elif wait and done is not None:
            done.wait(timeout=120)

    def _refresh_now(self) -> None:
        try:
            with self._lock:
                gen = self._generation
                top = sorted(self._counts.items(), key=lambda kv: kv[1],
                             reverse=True)[:self.capacity]
                keys = [k for k, _ in top]
            if not keys:
                return
            handles, nbytes = self.pin_fn(keys)
            with self._lock:
                if gen != self._generation:
                    return  # flushed (rebind) while we were pinning
                self._pinned = dict(handles)
                self._bytes = int(nbytes)
                self._refreshes += 1
        except Exception as e:  # noqa: BLE001 — a failed pin only
            log.warning("hot-entity pin refresh failed: %s", e)  # loses
        finally:                                  # the fast path, never
            with self._lock:                      # breaks serving
                self._refreshing = False
                if self._refresh_done is not None:
                    self._refresh_done.set()

    def invalidate(self, keys) -> int:
        """Drop the pinned handles for ``keys`` only — their factor
        rows changed under the pin (a streaming fold-in rewrote them,
        ISSUE 10) so a pinned serve would read the OLD rows. Hit stats
        survive: the entities are as hot as ever and the next refresh
        re-pins them from the updated table."""
        dropped = 0
        with self._lock:
            for k in keys:
                if self._pinned.pop(k, None) is not None:
                    dropped += 1
        return dropped

    def flush(self) -> int:
        """Drop pins and hit stats (model rebind / operator flush)."""
        with self._lock:
            n = len(self._pinned)
            self._pinned = {}
            self._counts = {}
            self._bytes = 0
            self._generation += 1
        return n

    # -- observability ------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            hits, misses = self._hits, self._misses
            out = {
                "entries": len(self._pinned),
                "bytes": self._bytes,
                "capacity": self.capacity,
                "hits": hits,
                "misses": misses,
                "evictions": 0,
                "invalidations": self._generation,
                "records": self._records,
                "refreshes": self._refreshes,
                "trackedEntities": len(self._counts),
            }
        total = hits + misses
        out["hitRatio"] = (hits / total) if total else 0.0
        return out
