"""Invalidation bus: the event server tells caches what just changed.

On every accepted ingest the event server publishes
``(app_id, entity_type, entity_id, event_name)``; each subscribed
serving cache invalidates the entries whose tags cover that entity —
so a cached recommendation for ``u42`` dies the moment ``u42``'s next
``view`` event lands, long before the TTL staleness bound.

Delivery is **synchronous and in-process**: by the time the ingest
HTTP response is written, every subscriber has been invalidated —
which is what lets tests (and operators) reason "ingest returned ⇒ no
later query serves the pre-ingest result". Deployments that run the
event server in a *different process* from the engine server fall
back to the TTL bound (see docs/serving-cache.md).

Subscribers are held by **weakref**: a test or bench that drops its
``QueryServer`` must not leave a dead cache wired into the
process-global bus forever.
"""

from __future__ import annotations

import logging
import threading
import weakref

from ..concurrency import new_lock
from typing import Any, Callable, List, Optional

log = logging.getLogger(__name__)

__all__ = ["InvalidationBus", "default_bus"]

#: subscriber signature: (app_id, entity_type, entity_id, event_name)
Subscriber = Callable[[Optional[int], str, str, str], Any]


class InvalidationBus:
    def __init__(self) -> None:
        self._lock = new_lock("InvalidationBus._lock")
        self._subs: List[weakref.ref] = []
        self._published = 0
        self._delivered = 0

    def subscribe(self, owner: Any, method_name: str = "on_event") -> None:
        """Subscribe ``owner.<method_name>``; ``owner`` is held weakly
        (bound methods would keep the owner alive through the bus —
        ``WeakMethod`` keeps the reference honest)."""
        ref = weakref.WeakMethod(getattr(owner, method_name))
        with self._lock:
            self._subs.append(ref)

    def unsubscribe(self, owner: Any,
                    method_name: str = "on_event") -> None:
        target = getattr(owner, method_name, None)
        with self._lock:
            self._subs = [r for r in self._subs
                          if r() is not None and r() != target]

    def publish(self, app_id: Optional[int], entity_type: str,
                entity_id: str, event_name: str = "") -> int:
        """Deliver to every live subscriber; returns how many were
        reached. A failing subscriber is logged and skipped — ingest
        must never fail because a cache hiccuped."""
        with self._lock:
            refs = list(self._subs)
        delivered = 0
        dead = False
        for ref in refs:
            fn = ref()
            if fn is None:
                dead = True
                continue
            try:
                fn(app_id, entity_type, entity_id, event_name)
                delivered += 1
            except Exception as e:  # noqa: BLE001 — ingest goes on
                log.error("cache invalidation subscriber failed: %s", e)
        if dead:
            with self._lock:
                self._subs = [r for r in self._subs if r() is not None]
        with self._lock:
            self._published += 1
            self._delivered += delivered
        return delivered

    def publish_many(self, app_id: Optional[int],
                     items: List[tuple]) -> int:
        """Coalesced multi-entity publish: deliver every
        ``(entity_type, entity_id, event_name)`` of one accepted batch
        with ONE subscriber snapshot and one stats update, instead of
        a full :meth:`publish` (two lock passes + dead-ref sweep) per
        item — the event server's batch/webhook ingest path. Per-item
        delivery to each subscriber is preserved, so tag semantics are
        exactly those of N single publishes."""
        if not items:
            return 0
        with self._lock:
            refs = list(self._subs)
        delivered = 0
        dead = False
        for ref in refs:
            fn = ref()
            if fn is None:
                dead = True
                continue
            for entity_type, entity_id, event_name in items:
                try:
                    fn(app_id, entity_type, entity_id, event_name)
                    delivered += 1
                except Exception as e:  # noqa: BLE001 — ingest goes on
                    log.error("cache invalidation subscriber failed: %s",
                              e)
        if dead:
            with self._lock:
                self._subs = [r for r in self._subs if r() is not None]
        with self._lock:
            self._published += len(items)
            self._delivered += delivered
        return delivered

    def subscriber_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._subs if r() is not None)

    def stats(self) -> dict:
        with self._lock:
            return {"subscribers": sum(1 for r in self._subs
                                       if r() is not None),
                    "published": self._published,
                    "delivered": self._delivered}


_default: Optional[InvalidationBus] = None
_default_lock = threading.Lock()  # import-time; predates any instrumentation flip


def default_bus() -> InvalidationBus:
    """The process-wide bus: event-server ingest publishes here and
    every serving cache subscribes here unless given its own."""
    global _default
    with _default_lock:
        if _default is None:
            _default = InvalidationBus()
        return _default
