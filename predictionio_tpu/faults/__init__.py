"""Fault injection for failure drills (ISSUE 11, docs/reliability.md).

A process-wide registry of *named injection points* threaded through
the subsystems that matter for elasticity — storage I/O, device
dispatch, serving lanes, stream-trainer passes, checkpoint
save/commit/restore, multihost collectives — so tests and CI drills
script **real** failures (a storage backend that raises, a serving lane
that dies, a process that vanishes mid-checkpoint) instead of mocks.

Zero overhead when off: every instrumented site calls :func:`fire`,
which is a single global-bool check until something is injected.
"""

from .registry import (
    FaultError,
    FaultSpec,
    POINTS,
    clear,
    declare,
    enabled,
    fire,
    inject,
    inject_spec,
    parse_specs,
    registry,
    status,
)

__all__ = [
    "FaultError",
    "FaultSpec",
    "POINTS",
    "clear",
    "declare",
    "enabled",
    "fire",
    "inject",
    "inject_spec",
    "parse_specs",
    "registry",
    "status",
]
