"""The fault registry: named injection points, seeded schedules.

Design constraints (docs/reliability.md):

- **Fast when off.** ``fire()`` is one module-global bool check until a
  spec is armed; instrumented hot paths (storage I/O, lane dispatch)
  pay nothing in production.
- **Deterministic.** Every spec owns a ``random.Random(seed)`` — a
  ``rate=0.3,seed=7`` schedule injects the *same* sequence of fires on
  every run, so a CI drill that passed yesterday fails for a real
  reason today.
- **Scriptable from outside.** ``PTPU_FAULTS`` (and
  ``ServerConfig.faults`` / ``ptpu deploy --faults``) carries a spec
  grammar so a drill can arm a child process it is about to start
  without patching code::

      PTPU_FAULTS="checkpoint.commit=crash,after=2;storage.io=error,rate=0.5,seed=3"

  Grammar: ``point=mode[,key=value...]`` joined by ``;``. Modes:
  ``error`` (raise :class:`FaultError`), ``latency`` (sleep
  ``delay_ms`` then proceed), ``crash`` (``os._exit(42)`` — the
  preemption/`kill -9` simulator). Keys: ``rate`` (probability per
  matching fire, default 1), ``times`` (stop after N injections,
  default unlimited), ``after`` (skip the first N matching fires),
  ``delay_ms``, ``seed``, and any other key is a label match
  (``serving.lane=error,lane=1`` only fails lane 1).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

#: exit code of a ``crash``-mode injection — drills assert on it to
#: tell a scripted preemption from a real interpreter fault
CRASH_EXIT_CODE = 42

#: catalog of declared injection points (name → description), built by
#: the instrumented modules at import time; ``ptpu check`` docs and
#: docs/reliability.md list these
POINTS: Dict[str, str] = {}


def declare(point: str, description: str) -> str:
    """Register an injection point in the catalog (idempotent)."""
    POINTS.setdefault(point, description)
    return point


class FaultError(RuntimeError):
    """An injected failure (mode=``error``). Carries the point name so
    handlers/telemetry can attribute it."""

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"injected fault at {point}")
        self.point = point


@dataclass
class FaultSpec:
    """One armed injection: where, how, and on what schedule."""

    point: str                 # point name or fnmatch glob
    mode: str = "error"        # error | latency | crash
    rate: float = 1.0          # probability per matching fire
    times: int = -1            # max injections (-1 = unlimited)
    after: int = 0             # skip the first N matching fires
    delay_ms: float = 0.0      # latency mode: sleep this long
    seed: int = 0              # deterministic schedule
    message: str = ""
    match: Dict[str, str] = field(default_factory=dict)  # label filters

    def __post_init__(self) -> None:
        if self.mode not in ("error", "latency", "crash"):
            raise ValueError(
                f"fault mode must be error|latency|crash, got "
                f"{self.mode!r}")
        if not (0.0 <= self.rate <= 1.0):
            raise ValueError(f"fault rate must be in [0,1]: {self.rate}")


class _Armed:
    """A spec plus its live schedule state."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.seen = 0       # matching fires observed
        self.injected = 0   # injections delivered

    def decide(self, point: str, labels: Dict[str, str]) -> bool:
        s = self.spec
        if not fnmatchcase(point, s.point):
            return False
        for k, v in s.match.items():
            if str(labels.get(k)) != v:
                return False
        self.seen += 1
        if self.seen <= s.after:
            return False
        if s.times >= 0 and self.injected >= s.times:
            return False
        if s.rate < 1.0 and self.rng.random() >= s.rate:
            return False
        self.injected += 1
        return True


class FaultRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed: List[_Armed] = []
        self._fired: Dict[str, int] = {}       # point → fires observed
        self._injections: Dict[str, int] = {}  # "point|mode" → count
        self._listeners: List[Callable[[str, str], None]] = []
        self._env_loaded = False

    # -- arming ------------------------------------------------------------
    def inject(self, spec: FaultSpec) -> FaultSpec:
        global _ACTIVE
        with self._lock:
            self._armed.append(_Armed(spec))
            _ACTIVE = True
        log.warning("fault armed: %s mode=%s rate=%s times=%s after=%s "
                    "match=%s", spec.point, spec.mode, spec.rate,
                    spec.times, spec.after, spec.match)
        return spec

    def clear(self, point: Optional[str] = None) -> int:
        """Disarm every spec (or only those for ``point``); returns how
        many were removed."""
        global _ACTIVE
        with self._lock:
            before = len(self._armed)
            if point is None:
                self._armed = []
            else:
                self._armed = [a for a in self._armed
                               if a.spec.point != point]
            _ACTIVE = bool(self._armed)
            return before - len(self._armed)

    def load_env(self, env_var: str = "PTPU_FAULTS") -> None:
        """Arm specs from the environment ONCE per process."""
        with self._lock:
            if self._env_loaded:
                return
            self._env_loaded = True
        raw = os.environ.get(env_var, "")
        if not raw:
            return
        for spec in parse_specs(raw):
            self.inject(spec)

    # -- firing ------------------------------------------------------------
    def fire(self, point: str, **labels) -> None:
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            hits = [a for a in self._armed if a.decide(point, labels)]
            for a in hits:
                key = f"{point}|{a.spec.mode}"
                self._injections[key] = self._injections.get(key, 0) + 1
            listeners = list(self._listeners) if hits else []
        for a in hits:
            for cb in listeners:
                try:
                    cb(point, a.spec.mode)
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
            mode = a.spec.mode
            if mode == "latency":
                time.sleep(max(a.spec.delay_ms, 0.0) / 1000.0)
            elif mode == "crash":
                log.error("injected crash at %s (exit %d)", point,
                          CRASH_EXIT_CODE)
                # the preemption simulator: no atexit, no finally — the
                # process is GONE, exactly like kill -9 / a reclaimed
                # preemptible host
                os._exit(CRASH_EXIT_CODE)
            else:
                raise FaultError(point, a.spec.message)

    # -- observability -----------------------------------------------------
    def add_listener(self, cb: Callable[[str, str], None]) -> None:
        """``cb(point, mode)`` on every delivered injection (metrics)."""
        with self._lock:
            self._listeners.append(cb)

    def enabled(self) -> bool:
        with self._lock:
            return bool(self._armed)

    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": bool(self._armed),
                "armed": [{
                    "point": a.spec.point, "mode": a.spec.mode,
                    "rate": a.spec.rate, "times": a.spec.times,
                    "after": a.spec.after, "match": dict(a.spec.match),
                    "seen": a.seen, "injected": a.injected,
                } for a in self._armed],
                "fired": dict(self._fired),
                "injections": dict(self._injections),
            }


def parse_specs(raw: str) -> List[FaultSpec]:
    """Parse the ``PTPU_FAULTS`` grammar (module docstring)."""
    out: List[FaultSpec] = []
    for chunk in raw.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        point, _, rest = chunk.partition("=")
        if not point or not rest:
            raise ValueError(
                f"bad fault spec {chunk!r} (want point=mode[,k=v...])")
        parts = rest.split(",")
        kwargs: dict = {"point": point.strip(), "mode": parts[0].strip()}
        match: Dict[str, str] = {}
        for kv in parts[1:]:
            k, _, v = kv.partition("=")
            k, v = k.strip(), v.strip()
            if not k or not v:
                raise ValueError(f"bad fault option {kv!r} in {chunk!r}")
            if k in ("rate", "delay_ms"):
                kwargs[k] = float(v)
            elif k in ("times", "after", "seed"):
                kwargs[k] = int(v)
            elif k == "message":
                kwargs[k] = v
            else:
                match[k] = v
        kwargs["match"] = match
        out.append(FaultSpec(**kwargs))
    return out


#: the ONE fast-path gate: False ⇒ fire() returns before touching the
#: registry lock — instrumented hot paths stay free in production
_ACTIVE = False

_REGISTRY = FaultRegistry()
_REGISTRY.load_env()


def registry() -> FaultRegistry:
    return _REGISTRY


def fire(point: str, **labels) -> None:
    """The instrumented-site entry: no-op unless something is armed."""
    if not _ACTIVE:
        return
    _REGISTRY.fire(point, **labels)


def inject(point: str, mode: str = "error", **kwargs) -> FaultSpec:
    return _REGISTRY.inject(FaultSpec(point=point, mode=mode, **kwargs))


def inject_spec(raw: str) -> List[FaultSpec]:
    """Arm every spec in a ``PTPU_FAULTS``-grammar string."""
    return [_REGISTRY.inject(s) for s in parse_specs(raw)]


def clear(point: Optional[str] = None) -> int:
    return _REGISTRY.clear(point)


def enabled() -> bool:
    return _REGISTRY.enabled()


def status() -> dict:
    return _REGISTRY.status()
