"""pypio-compatible surface for users migrating from the reference.

The reference's Python story was a py4j bridge into the JVM
(``python/pypio/data/eventstore.py:26-48`` → ``PPythonEventStore`` →
Spark DataFrame; SURVEY C27). This framework IS Python, so the bridge
collapses to thin aliases over the native facade — same call names, no
py4j, events come back as host rows ready for ``numpy``/``jax``.

    from predictionio_tpu.pypio import p_event_store
    rows = p_event_store.find(app_name="myapp")
    props = p_event_store.aggregate_properties("myapp", "user")

``find`` returns a list of ``Event``s (the DataFrame role is played by
converting to columnar numpy with ``events_to_columns``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .data.event import Event
from .data.store import EventStoreFacade, event_store


class PEventStore:
    """Name-compatible with ``pypio.data.PEventStore``."""

    def __init__(self, facade: Optional[EventStoreFacade] = None):
        self._facade = facade or event_store

    def find(self, app_name: str, channel_name: Optional[str] = None,
             **filters) -> List[Event]:
        return list(self._facade.find(app_name, channel_name=channel_name,
                                      **filters))

    def aggregate_properties(self, app_name: str, entity_type: str,
                             channel_name: Optional[str] = None,
                             **filters):
        return self._facade.aggregate_properties(
            app_name, entity_type, channel_name=channel_name, **filters)


def events_to_columns(events: Sequence[Event]) -> Dict[str, np.ndarray]:
    """Columnar view of an event list (the Spark-DataFrame role): object
    arrays for ids/names, int64 millis for times."""
    return {
        "event": np.array([e.event for e in events], dtype=object),
        "entityType": np.array([e.entity_type for e in events],
                               dtype=object),
        "entityId": np.array([e.entity_id for e in events], dtype=object),
        "targetEntityType": np.array(
            [e.target_entity_type for e in events], dtype=object),
        "targetEntityId": np.array(
            [e.target_entity_id for e in events], dtype=object),
        "eventTime": np.array([e.event_time_millis for e in events],
                              dtype=np.int64),
    }


#: module-level instance, mirroring `pypio`'s usage style
p_event_store = PEventStore()
