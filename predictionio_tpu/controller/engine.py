"""Engine: binds named DASE component classes and runs generic train/eval.

Capability parity with the reference ``Engine``
(``controller/Engine.scala:82-88`` class maps; generic ``train`` :623-710
with sanity checks and stop-after-read/prepare; generic ``eval`` :728-817
k-fold × algorithms with union/served predictions; ``prepareDeploy``
:198-267 covering the three persistence flavors).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type, Union

from .base import (
    Algorithm,
    DataSource,
    PersistentModelManifest,
    Preparator,
    SanityCheck,
    Serving,
)
from .context import Context
from .params import EngineParams, engine_params_from_variant, instantiate

log = logging.getLogger(__name__)

ClassMap = Union[Type, Dict[str, Type]]


def _as_map(x: ClassMap) -> Dict[str, Type]:
    return x if isinstance(x, dict) else {"": x}


def _sanity(obj: Any, what: str, skip: bool) -> None:
    if skip:
        return
    if isinstance(obj, SanityCheck):
        log.info("sanity check %s", what)
        obj.sanity_check()


@dataclass
class TrainResult:
    """Everything ``train`` produced: per-algorithm models in params order."""

    models: List[Any]
    engine_params: EngineParams


class Engine:
    """Named class maps for every DASE slot + generic train/eval."""

    def __init__(self,
                 datasource_classes: ClassMap,
                 preparator_classes: ClassMap,
                 algorithm_classes: ClassMap,
                 serving_classes: ClassMap,
                 datasource_params_class: Optional[Type] = None,
                 preparator_params_class: Optional[Type] = None,
                 algorithm_params_classes: Optional[Dict[str, Type]] = None,
                 serving_params_class: Optional[Type] = None):
        self.datasource_classes = _as_map(datasource_classes)
        self.preparator_classes = _as_map(preparator_classes)
        self.algorithm_classes = _as_map(algorithm_classes)
        self.serving_classes = _as_map(serving_classes)
        self.datasource_params_class = datasource_params_class
        self.preparator_params_class = preparator_params_class
        self.algorithm_params_classes = algorithm_params_classes or {}
        self.serving_params_class = serving_params_class

    # -- component instantiation ------------------------------------------
    def _make(self, classes: Dict[str, Type], pair: Tuple[str, Any], slot: str):
        name, params = pair
        if name not in classes:
            raise KeyError(f"{slot} {name!r} not registered "
                           f"(available: {sorted(classes)})")
        return instantiate(classes[name], params)

    def make_datasource(self, ep: EngineParams) -> DataSource:
        return self._make(self.datasource_classes, ep.datasource, "datasource")

    def make_preparator(self, ep: EngineParams) -> Preparator:
        return self._make(self.preparator_classes, ep.preparator, "preparator")

    def make_algorithms(self, ep: EngineParams) -> List[Algorithm]:
        return [self._make(self.algorithm_classes, pair, "algorithm")
                for pair in ep.algorithms]

    def make_serving(self, ep: EngineParams) -> Serving:
        return self._make(self.serving_classes, ep.serving, "serving")

    def params_from_variant(self, variant: dict) -> EngineParams:
        return engine_params_from_variant(
            variant,
            datasource_params_cls=self.datasource_params_class,
            preparator_params_cls=self.preparator_params_class,
            algorithm_params_classes=self.algorithm_params_classes,
            serving_params_cls=self.serving_params_class)

    # -- train (controller/Engine.scala:623-710) ---------------------------
    def train(self, ctx: Context, engine_params: EngineParams) -> TrainResult:
        import time as _time

        stages = ctx.stage_timings
        t0 = _time.monotonic()
        datasource = self.make_datasource(engine_params)
        td = datasource.read_training(ctx)
        stages["read_s"] = round(_time.monotonic() - t0, 2)
        _sanity(td, "training data", ctx.skip_sanity_check)
        if ctx.stop_after_read:
            log.info("stopping after read")
            return TrainResult(models=[], engine_params=engine_params)

        t0 = _time.monotonic()
        preparator = self.make_preparator(engine_params)
        pd = preparator.prepare(ctx, td)
        stages["prepare_s"] = round(_time.monotonic() - t0, 2)
        _sanity(pd, "prepared data", ctx.skip_sanity_check)
        if ctx.stop_after_prepare:
            log.info("stopping after prepare")
            return TrainResult(models=[], engine_params=engine_params)

        models = []
        t0 = _time.monotonic()
        for i, algo in enumerate(self.make_algorithms(engine_params)):
            log.info("training algorithm %d: %s", i, type(algo).__name__)
            model = algo.train(ctx, pd)
            _sanity(model, f"model[{i}]", ctx.skip_sanity_check)
            models.append(model)
        stages["algo_train_s"] = round(_time.monotonic() - t0, 2)
        return TrainResult(models=models, engine_params=engine_params)

    # -- eval (controller/Engine.scala:728-817) ----------------------------
    def eval(self, ctx: Context, engine_params: EngineParams
             ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """Returns per-fold ``(eval_info, [(query, served prediction,
        actual)])``. Trains every algorithm on every fold (the reference's
        k × algos trainings), predicts with ``batch_predict``, and serves
        the aligned per-algo predictions."""
        datasource = self.make_datasource(engine_params)
        folds = datasource.read_eval(ctx)
        preparator = self.make_preparator(engine_params)
        serving = self.make_serving(engine_params)
        results = []
        for fold_i, (td, eval_info, qa) in enumerate(folds):
            pd = preparator.prepare(ctx, td)
            queries = [serving.supplement(q) for q, _ in qa]
            actuals = [a for _, a in qa]
            per_algo: List[List[Any]] = []
            for algo in self.make_algorithms(engine_params):
                model = algo.train(ctx, pd)
                per_algo.append(algo.batch_predict(model, queries))
            served = [serving.serve(q, [preds[i] for preds in per_algo])
                      for i, q in enumerate(queries)]
            results.append((eval_info, list(zip(queries, served, actuals))))
            log.info("eval fold %d: %d queries", fold_i, len(queries))
        return results

    def batch_eval(self, ctx: Context, params_list: Sequence[EngineParams]
                   ) -> List[Tuple[EngineParams, list]]:
        """Evaluate every params set (``BaseEngine.batchEval``,
        ``core/BaseEngine.scala:82-91``)."""
        return [(ep, self.eval(ctx, ep)) for ep in params_list]

    # -- deploy-time model re-materialization (Engine.scala:198-267) -------
    def prepare_deploy(self, ctx: Context, engine_params: EngineParams,
                       stored_models: List[Any],
                       engine_instance_id: str) -> List[Any]:
        """Turn persisted model stand-ins back into live models:
        manifest → algorithm's custom loader; None → retrain (the
        reference's Unit-model path); otherwise the algorithm's
        ``load_persistent_model`` moves blobs back to device."""
        algos = self.make_algorithms(engine_params)
        if len(stored_models) != len(algos):
            raise ValueError(f"{len(stored_models)} stored models for "
                             f"{len(algos)} algorithms")
        needs_retrain = any(m is None for m in stored_models)
        retrained: Optional[List[Any]] = None
        if needs_retrain:
            log.info("ephemeral model(s) present; retraining for deploy")
            retrained = self.train(ctx, engine_params).models
        out = []
        for i, (algo, stored) in enumerate(zip(algos, stored_models)):
            if stored is None:
                assert retrained is not None
                out.append(retrained[i])
            else:
                # blob or PersistentModelManifest alike: the algorithm's
                # loader inverts whatever its make_persistent_model produced
                out.append(algo.load_persistent_model(ctx, stored))
        return out


class SimpleEngine(Engine):
    """Single-class engine with identity prep/first serving
    (``controller/EngineParams.scala:130``)."""

    def __init__(self, datasource_class: Type, algorithm_class: Type, **kw):
        from .base import FirstServing, IdentityPreparator
        super().__init__(
            datasource_classes=datasource_class,
            preparator_classes=IdentityPreparator,
            algorithm_classes=algorithm_class,
            serving_classes=FirstServing, **kw)


class EngineFactory:
    """Convention object templates export (``controller/EngineFactory.scala:31``):
    subclass or provide a callable returning an :class:`Engine`."""

    def apply(self) -> Engine:
        raise NotImplementedError
