"""Evaluation & hyperparameter tuning.

Capability parity with the reference's tuning stack:
``Evaluation`` couples an engine with metrics
(``controller/Evaluation.scala:34-125``); ``EngineParamsGenerator`` yields
the search list (``EngineParamsGenerator.scala:35-41``); ``MetricEvaluator``
scores every params set and picks the best by ``metric.compare``
(``controller/MetricEvaluator.scala:218-262``, best at :246-249, JSON
artifacts at :64-110,193-216).

Improvement over the reference (SURVEY §7 hard part 4): pipeline-prefix
memoization is built in — the reference recomputes DataSource/Preparator
(and retrains unchanged algorithms) for every entry of the search grid
unless templates opt into the experimental ``FastEvalEngine``
(``controller/FastEvalEngine.scala:52-210``); here the evaluator memoizes
(datasource params → folds) and (…+preparator params → prepared folds) and
(…+algorithm params → trained models) keyed by the params JSON.
"""

from __future__ import annotations

import json
import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .context import Context
from .engine import Engine
from .metric import Metric
from .params import EngineParams, params_to_json

log = logging.getLogger(__name__)


#: compute-once prefix caches — the concurrent analogue of sequential
#: memoization, so a parallel sweep still trains each (datasource,
#: preparator, algorithm) prefix exactly once (the FastEvalEngine
#: property, ``controller/FastEvalEngine.scala:87-210``)
from ..utils.memo import ComputeOnce as _Memo  # noqa: E402


class EngineParamsGenerator:
    """Subclass and set ``engine_params_list``."""

    engine_params_list: Sequence[EngineParams] = ()


@dataclass
class Evaluation:
    """An engine + metric(s) to optimize (``controller/Evaluation.scala``)."""

    engine: Engine
    metric: Metric
    other_metrics: Sequence[Metric] = ()

    @property
    def metrics(self) -> List[Metric]:
        return [self.metric, *self.other_metrics]


@dataclass
class MetricScores:
    engine_params: EngineParams
    score: float
    other_scores: List[float]
    train_s: float = 0.0
    eval_s: float = 0.0


@dataclass
class MetricEvaluatorResult:
    """Outcome of a sweep (``MetricEvaluator.scala:64-110``)."""

    best_score: float
    best_engine_params: EngineParams
    best_index: int
    metric_header: str
    other_metric_headers: List[str]
    scores: List[MetricScores] = field(default_factory=list)

    def to_one_liner(self) -> str:
        return (f"[{self.metric_header}] best variant {self.best_index}: "
                f"{self.best_score:.6f}")

    def to_json(self) -> str:
        return json.dumps({
            "bestScore": self.best_score,
            "bestIndex": self.best_index,
            "bestEngineParams": self.best_engine_params.to_json(),
            "metricHeader": self.metric_header,
            "otherMetricHeaders": self.other_metric_headers,
            "metricScoresList": [
                {"score": s.score, "otherScores": s.other_scores,
                 "engineParams": s.engine_params.to_json(),
                 "trainS": s.train_s, "evalS": s.eval_s}
                for s in self.scores],
        }, indent=2)

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{i}</td><td>{s.score:.6f}</td>"
            f"<td><pre>{json.dumps(s.engine_params.to_json(), indent=1)}"
            f"</pre></td></tr>"
            for i, s in enumerate(self.scores))
        return (f"<html><body><h1>{self.metric_header}</h1>"
                f"<p>{self.to_one_liner()}</p>"
                f"<table border=1><tr><th>#</th><th>score</th>"
                f"<th>params</th></tr>{rows}</table></body></html>")


def _key(pair: Any) -> str:
    """Cache key for a (name, params) slot pair."""
    name, params = pair
    return json.dumps(
        [name, params_to_json(params) if params is not None else None],
        sort_keys=True, default=str)


class MetricEvaluator:
    """Scores every engine-params set; memoizes shared pipeline
    prefixes. ``parallelism>1`` walks the grid with a thread pool (the
    reference's ``.par`` map, ``MetricEvaluator.scala:224-231`` — device
    work serializes on the accelerator anyway, but host-side packing,
    prediction decoding and metric math overlap across grid points).
    Opt-in: user DataSource/Algorithm/storage code written for the
    sequential contract must not be run concurrently by default."""

    def __init__(self, evaluation: Evaluation,
                 parallelism: Optional[int] = None):
        self.evaluation = evaluation
        self.parallelism = parallelism if parallelism is not None else 1

    def evaluate(self, ctx: Context,
                 params_list: Sequence[EngineParams]) -> MetricEvaluatorResult:
        engine = self.evaluation.engine
        metric = self.evaluation.metric
        fold_cache = _Memo()
        prep_cache = _Memo()
        model_cache = _Memo()

        def score_one(idx: int, ep: EngineParams) -> MetricScores:
            t0 = time.monotonic()
            ds_key = _key(ep.datasource)
            folds = fold_cache.get(
                ds_key, lambda: engine.make_datasource(ep).read_eval(ctx))
            if not folds:
                raise ValueError(
                    "DataSource.read_eval returned no folds; evaluation "
                    "requires read_eval to be implemented")

            prep_key = ds_key + "|" + _key(ep.preparator)
            prepared = prep_cache.get(prep_key, lambda: [
                engine.make_preparator(ep).prepare(ctx, td)
                for td, _, _ in folds])

            serving = engine.make_serving(ep)
            eval_data = []
            t_train = 0.0
            t_blocked = 0.0  # waiting on another thread's memoized work
            for fold_i, (pd, (td, ei, qa)) in enumerate(zip(prepared, folds)):
                queries = [serving.supplement(q) for q, _ in qa]
                actuals = [a for _, a in qa]
                per_algo = []
                for algo_pair, algo in zip(ep.algorithms,
                                           engine.make_algorithms(ep)):
                    m_key = prep_key + f"|f{fold_i}|" + _key(algo_pair)
                    w0 = time.monotonic()
                    model, spent = model_cache.get_timed(
                        m_key, lambda: algo.train(ctx, pd))
                    t_train += spent
                    t_blocked += (time.monotonic() - w0) - spent
                    per_algo.append(algo.batch_predict(model, queries))
                served = [serving.serve(q, [p[i] for p in per_algo])
                          for i, q in enumerate(queries)]
                eval_data.append((ei, list(zip(queries, served, actuals))))

            score = metric.calculate(eval_data)
            others = [m.calculate(eval_data)
                      for m in self.evaluation.other_metrics]
            log.info("params %d/%d: %s = %f", idx + 1, len(params_list),
                     metric.header, score)
            return MetricScores(
                engine_params=ep, score=score, other_scores=others,
                train_s=t_train,
                eval_s=time.monotonic() - t0 - t_blocked)

        workers = max(1, int(self.parallelism))
        if workers <= 1 or len(params_list) <= 1:
            scores = [score_one(i, ep) for i, ep in enumerate(params_list)]
        else:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                scores = list(pool.map(score_one, range(len(params_list)),
                                       params_list))

        best_index = 0
        for i in range(1, len(scores)):
            if metric.compare(scores[i].score, scores[best_index].score) > 0:
                best_index = i
        best = scores[best_index]
        return MetricEvaluatorResult(
            best_score=best.score,
            best_engine_params=best.engine_params,
            best_index=best_index,
            metric_header=metric.header,
            other_metric_headers=[m.header for m in
                                  self.evaluation.other_metrics],
            scores=scores)


def save_best_variant_json(result: MetricEvaluatorResult, path: str,
                           base_variant: Optional[dict] = None) -> None:
    """Write the winning params as an engine-variant JSON
    (``MetricEvaluator.saveEngineJson``, :193-216)."""
    ep = result.best_engine_params.to_json()
    variant = dict(base_variant or {})
    variant.update({
        "datasource": ep["dataSourceParams"],
        "preparator": ep["preparatorParams"],
        "algorithms": ep["algorithmsParams"],
        "serving": ep["servingParams"],
    })
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(variant, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
