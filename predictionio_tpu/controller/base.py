"""DASE controller contracts: DataSource, Preparator, Algorithm, Serving.

Capability parity with the reference's controller API
(``core/.../core/BaseDataSource.scala:34-55``, ``BasePreparator.scala:33-45``,
``BaseAlgorithm.scala:58-126``, ``BaseServing.scala:31-54``), with the
L/P/P2L split collapsed: the reference needed three flavors of every
controller because models lived either on the Spark driver (L), across
executors as RDDs (P), or were trained parallel and collected local (P2L)
(``controller/{LAlgorithm,PAlgorithm,P2LAlgorithm}.scala``). Here a model is
a pytree of (possibly sharded) ``jax.Array``s; mesh size 1..N covers all
three cases with one API.

Type parameters used informally throughout (Python generics kept light):
TD training data, PD prepared data, M model, Q query, P prediction,
A actual (ground truth), EI eval info.
"""

from __future__ import annotations

import abc
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

from .context import Context

TD = TypeVar("TD")
PD = TypeVar("PD")
M = TypeVar("M")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")
EI = TypeVar("EI")

#: One evaluation fold: (training data, eval info, [(query, actual)]).
EvalFold = Tuple[TD, EI, List[Tuple[Q, A]]]


class SanityCheck(abc.ABC):
    """Optional self-check hook on data/model objects
    (``controller/SanityCheck.scala``); the workflow calls it after read,
    prepare, and train unless skipped."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise if the object is malformed (e.g. empty training data)."""


class DataSource(abc.ABC, Generic[TD, EI, Q, A]):
    """Reads training and evaluation data from the event store
    (``core/BaseDataSource.scala:43,54``)."""

    @abc.abstractmethod
    def read_training(self, ctx: Context) -> TD:
        ...

    def read_eval(self, ctx: Context) -> List[EvalFold]:
        """Folds of (TD, EI, [(Q, A)]) for evaluation; default: none."""
        return []


class Preparator(abc.ABC, Generic[TD, PD]):
    """Transforms training data into algorithm input
    (``core/BasePreparator.scala:44``)."""

    @abc.abstractmethod
    def prepare(self, ctx: Context, training_data: TD) -> PD:
        ...


class IdentityPreparator(Preparator):
    """Pass-through preparator (``controller/IdentityPreparator.scala``)."""

    def __init__(self, params: Any = None):
        pass

    def prepare(self, ctx: Context, training_data):
        return training_data


class Algorithm(abc.ABC, Generic[PD, M, Q, P]):
    """The train/predict contract (``core/BaseAlgorithm.scala:69-126``).

    Models should be pytrees of arrays (sharded over ``ctx.mesh`` when
    large); ``predict`` should be thin host glue around jitted device code
    so serving stays low-latency.
    """

    @abc.abstractmethod
    def train(self, ctx: Context, prepared_data: PD) -> M:
        ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P:
        ...

    def batch_predict(self, model: M, queries: Sequence[Q]) -> List[P]:
        """Bulk prediction for eval/batch jobs
        (``core/BaseAlgorithm.scala:81``). Override with a vectorized/vmapped
        implementation where shapes allow; default is a host loop."""
        return [self.predict(model, q) for q in queries]

    # -- persistence flavor (core/BaseAlgorithm.scala:111-115) -------------
    def make_persistent_model(self, model: M, engine_instance_id: str,
                              algo_index: int) -> Any:
        """Decide how ``model`` persists. Return values:

        - the model itself (or any picklable stand-in): stored in the
          MODELDATA blob (reference default, Kryo → here pickled numpy
          pytrees);
        - a :class:`PersistentModelManifest`: the algorithm saved the model
          itself (custom checkpoint dir, Orbax, ...), only the manifest is
          stored;
        - ``None``: nothing persists; deploy retrains (reference ``Unit``
          model semantics, ``controller/Engine.scala:210-232``).

        Models implementing
        :class:`~predictionio_tpu.controller.persistent.PersistentModel`
        save themselves and persist as a manifest automatically
        (``Engine.makeSerializableModels`` :284).
        """
        from ..workflow.persistence import to_host
        from .persistent import PersistentModel, manifest_for
        if isinstance(model, PersistentModel):
            manifest = manifest_for(model, engine_instance_id, algo_index)
            if manifest is not None:
                return manifest
        return to_host(model)

    def bind_serving(self, ctx: Context) -> None:
        """Called on the instances that will actually serve queries (engine
        server bind/reload, batch predict) with the serving Context.
        Override to capture serving-time resources — e.g. the e-commerce
        template grabs ``ctx.event_store`` so its realtime filter reads hit
        the deployed storage, not the process-global default. No-op here."""

    def prepare_serving_model(self, model: M, max_batch: int = 1) -> M:
        """Called once per model when it binds to a serving surface
        (engine server bind/reload, batch predict) with the largest
        batch that surface coalesces. Override to fix the model's
        device placement — e.g. the recommendation template moves
        re-materialized factor matrices into HBM so the serving jits
        don't re-transfer host arrays on every query. Identity here."""
        return model

    def load_persistent_model(self, ctx: Context, stored: Any) -> M:
        """Invert :meth:`make_persistent_model` at deploy time."""
        from ..workflow.persistence import to_device
        from .persistent import load_from_manifest
        if isinstance(stored, PersistentModelManifest) and stored.class_name:
            return load_from_manifest(stored)
        return to_device(stored)

    #: Optional dataclass type for typed query parsing at the REST boundary
    #: (the reference's queryClass via reflection, BaseAlgorithm.scala:93).
    query_class: Optional[type] = None


class Serving(abc.ABC, Generic[Q, P]):
    """Combines per-algorithm predictions into the served result
    (``core/BaseServing.scala:41,53``)."""

    def supplement(self, query: Q) -> Q:
        """Pre-predict query enrichment (``BaseServing.supplementBase``)."""
        return query

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        ...


class FirstServing(Serving):
    """Serve the first algorithm's prediction
    (``controller/LFirstServing.scala``)."""

    def __init__(self, params: Any = None):
        pass

    def serve(self, query, predictions):
        return predictions[0]


class AverageServing(Serving):
    """Average numeric predictions (``controller/LAverageServing.scala``)."""

    def __init__(self, params: Any = None):
        pass

    def serve(self, query, predictions):
        return sum(predictions) / len(predictions)


class PersistentModelManifest:
    """Marker stored in place of a model blob when the algorithm persists
    its own model (``workflow/PersistentModelManifest``); records how to
    find it again. ``class_name`` (``module:QualName``) names a
    :class:`~predictionio_tpu.controller.persistent.PersistentModel`
    whose ``load`` inverts the save; ``location``/``extra`` cover ad-hoc
    layouts handled by a custom ``load_persistent_model`` override."""

    def __init__(self, class_name: str = "", engine_instance_id: str = "",
                 algo_index: int = 0, location: str = "",
                 extra: Optional[dict] = None):
        self.class_name = class_name
        self.engine_instance_id = engine_instance_id
        self.algo_index = algo_index
        self.location = location
        self.extra = extra or {}

    def __repr__(self):
        return (f"PersistentModelManifest({self.class_name!r}, "
                f"{self.engine_instance_id!r}, {self.algo_index}, "
                f"{self.location!r})")
