"""Custom model persistence: the PersistentModel protocol.

Capability parity with ``controller/PersistentModel.scala`` (+
``PersistentModelLoader``, ``LocalFileSystemPersistentModel``): a model
class that manages its own durable form. ``save`` runs at train time; at
deploy, the stored :class:`PersistentModelManifest` names the class,
whose ``load`` classmethod re-materializes the model
(``controller/Engine.scala:241-250``,
``workflow/WorkflowUtils.scala:350``).

Checkpoint layout: one ``<instanceId>-<algoIndex>.pkl`` per
(instance, algorithm) under ``$PIO_HOME/models/`` (or ``./.ptpu/models``)
— the role the reference's HDFS paths / LocalFS played. Device arrays
are converted to numpy before pickling so checkpoints stay portable
across mesh shapes.
"""

from __future__ import annotations

import abc
import importlib
import os
import pickle
from typing import Any, Optional

from .base import PersistentModelManifest


def models_dir() -> str:
    root = os.environ.get("PIO_HOME") or os.path.join(".", ".ptpu")
    path = os.path.join(root, "models")
    os.makedirs(path, exist_ok=True)
    return path


def model_path(engine_instance_id: str, algo_index: int = 0) -> str:
    """Per-(instance, algorithm) checkpoint path (the reference's
    ``(engineInstanceId, ax, algoName)`` id scheme,
    ``controller/Engine.scala:246,298``)."""
    return os.path.join(models_dir(),
                        f"{engine_instance_id}-{algo_index}")


class PersistentModel(abc.ABC):
    """Self-persisting model (``PersistentModel.scala``). Algorithms whose
    ``train`` returns one of these get manifest-based persistence
    automatically (see ``Algorithm.make_persistent_model``)."""

    @abc.abstractmethod
    def save(self, engine_instance_id: str, algo_index: int = 0) -> bool:
        """Persist. Return False to fall back to blob pickling."""

    @classmethod
    @abc.abstractmethod
    def load(cls, engine_instance_id: str,
             algo_index: int = 0) -> "PersistentModel":
        """Invert :meth:`save`."""


class LocalFileSystemPersistentModel(PersistentModel):
    """Pickle-to-local-disk base class
    (``controller/LocalFileSystemPersistentModel.scala``). Subclass and
    it just works; override ``save``/``load`` for custom layouts."""

    def persisted_location(self, engine_instance_id: str,
                           algo_index: int = 0) -> str:
        """Absolute checkpoint path, recorded in the manifest so deploy
        does not depend on PIO_HOME matching the training environment."""
        return os.path.abspath(
            model_path(engine_instance_id, algo_index) + ".pkl")

    def save(self, engine_instance_id: str, algo_index: int = 0) -> bool:
        import copy

        from ..workflow.persistence import to_host

        path = self.persisted_location(engine_instance_id, algo_index)
        # an instance is a single pytree leaf, so map to_host over its
        # attributes — that's where the device arrays live
        clone = copy.copy(self)
        clone.__dict__ = {k: to_host(v) for k, v in self.__dict__.items()}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(clone, f, protocol=4)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return True

    @classmethod
    def load_path(cls, path: str):
        with open(path, "rb") as f:
            model = pickle.load(f)
        if not isinstance(model, cls):
            raise TypeError(f"checkpoint at {path} holds "
                            f"{type(model).__name__}, expected "
                            f"{cls.__name__}")
        return model

    @classmethod
    def load(cls, engine_instance_id: str, algo_index: int = 0):
        return cls.load_path(
            os.path.abspath(model_path(engine_instance_id, algo_index)
                            + ".pkl"))


def manifest_for(model: PersistentModel, engine_instance_id: str,
                 algo_index: int) -> Optional[PersistentModelManifest]:
    """Run ``save``; on success return the manifest to store in place of
    the model (``Engine.makeSerializableModels`` :284-…)."""
    if model.save(engine_instance_id, algo_index):
        cls = type(model)
        locator = getattr(model, "persisted_location", None)
        return PersistentModelManifest(
            class_name=f"{cls.__module__}:{cls.__qualname__}",
            engine_instance_id=engine_instance_id,
            algo_index=algo_index,
            location=locator(engine_instance_id, algo_index)
            if locator else "")
    return None


def load_from_manifest(manifest: PersistentModelManifest) -> Any:
    """Resolve the manifest's class and call its loader
    (``SparkWorkflowUtils.getPersistentModel`` role)."""
    mod_name, qualname = manifest.class_name.split(":", 1)
    obj: Any = importlib.import_module(mod_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    # use the recorded absolute location (robust to a different PIO_HOME
    # at deploy) — but ONLY when the class kept the stock pickle loader;
    # a subclass overriding `load` owns its layout entirely
    stock_load = (getattr(obj, "load", None) is not None
                  and obj.load.__func__
                  is LocalFileSystemPersistentModel.load.__func__)
    if manifest.location and stock_load:
        return obj.load_path(manifest.location)
    return obj.load(manifest.engine_instance_id, manifest.algo_index)
