"""SelfCleaningDataSource: event-log compaction mixin.

Capability parity with ``core/SelfCleaningDataSource.scala``:
``EventWindow(duration, remove_duplicates, compress_properties)`` (:320),
recent-window filtering that always keeps ``$set``/``$unset`` events
(``getCleanedPEvents`` :77-86), ``$set``/``$unset`` property compression
into one event per entity (``compress`` :293-316), duplicate removal
keyed on everything except id/times (``removePDuplicates`` :127-133,
``recreateEvent`` :135-143), and persisted rewrite
(``cleanPersistedPEvents`` :160-174 / ``wipe``).

Deliberate deviation: the reference's local-path compression groups by
``entityType`` only (``compressLProperties`` :118-125), merging property
events of DIFFERENT entities of the same type — a reference defect. Both
paths here group by (entityType, entityId) like its parallel path.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from datetime import datetime, timedelta
from typing import Dict, Iterable, List, Optional, Tuple

from ..data.datamap import DataMap
from ..data.event import Event, utcnow

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class EventWindow:
    """``EventWindow`` (``SelfCleaningDataSource.scala:320-324``).
    ``duration`` accepts ``"<n> <unit>"`` (seconds/minutes/hours/days/
    weeks, singular or plural) or a bare number of seconds."""
    duration: Optional[str] = None
    remove_duplicates: bool = False
    compress_properties: bool = False


_UNITS = {"second": 1, "minute": 60, "hour": 3600, "day": 86400,
          "week": 604800}


def parse_duration(s: str) -> timedelta:
    s = s.strip()
    m = re.fullmatch(r"([0-9.]+)\s*([a-zA-Z]+)?", s)
    if not m:
        raise ValueError(f"cannot parse duration {s!r}")
    n = float(m.group(1))
    unit = (m.group(2) or "second").lower().rstrip("s")
    if unit not in _UNITS:
        raise ValueError(f"unknown duration unit in {s!r}")
    return timedelta(seconds=n * _UNITS[unit])


def _is_set_event(e: Event) -> bool:
    return e.event in ("$set", "$unset")


def _compress_group(events: List[Event]) -> Event:
    """Replay one entity's ``$set``/``$unset`` stream (ascending time)
    into a single ``$set`` carrying the final property state
    (``compress`` :293-316, in forward time order)."""
    props: Dict = {}
    last = events[-1]
    for e in events:
        if e.event == "$set":
            props.update(e.properties.to_dict())
        else:  # $unset
            for k in e.properties.to_dict():
                props.pop(k, None)
    return last.copy(event="$set", properties=DataMap(props),
                     event_id=None)


def _dedup_key(e: Event) -> Tuple:
    """Everything except eventId/eventTime/creationTime
    (``recreateEvent`` normalization, :135-143)."""
    import json
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id,
            json.dumps(e.properties.to_dict(), sort_keys=True, default=str),
            tuple(e.tags), e.pr_id)


class SelfCleaningDataSource:
    """Mixin for DataSources. Subclasses set ``app_name`` and override
    ``event_window``; call :meth:`clean_events` on what they read, or
    :meth:`clean_persisted_events` to rewrite storage in place."""

    app_name: str = ""

    @property
    def event_window(self) -> Optional[EventWindow]:
        return None

    # -- pure transformations ----------------------------------------------
    def filter_window(self, events: Iterable[Event],
                      now: Optional[datetime] = None) -> List[Event]:
        """Keep events inside the window; property events always survive
        (``getCleanedPEvents`` :77-86)."""
        ew = self.event_window
        events = list(events)
        if ew is None or ew.duration is None:
            return events
        cutoff = (now or utcnow()) - parse_duration(ew.duration)
        return [e for e in events
                if e.event_time > cutoff or _is_set_event(e)]

    def compress_properties(self, events: Iterable[Event]) -> List[Event]:
        """One compacted ``$set`` per (entityType, entityId)
        (``compressPProperties`` :106-116)."""
        groups: Dict[Tuple[str, str], List[Event]] = {}
        rest: List[Event] = []
        for e in sorted(events, key=lambda e: e.event_time):
            if _is_set_event(e):
                groups.setdefault((e.entity_type, e.entity_id),
                                  []).append(e)
            else:
                rest.append(e)
        return [_compress_group(g) for g in groups.values()] + rest

    def remove_duplicates(self, events: Iterable[Event]) -> List[Event]:
        """Keep the EARLIEST of each duplicate set
        (``removePDuplicates`` :127-133)."""
        seen: Dict[Tuple, Event] = {}
        for e in sorted(events, key=lambda e: e.event_time):
            seen.setdefault(_dedup_key(e), e)
        return list(seen.values())

    def clean_events(self, events: Iterable[Event],
                     now: Optional[datetime] = None) -> List[Event]:
        """window filter → optional compression → optional dedup
        (``cleanPEvents`` :227-242)."""
        ew = self.event_window
        out = self.filter_window(events, now=now)
        if ew is None:
            return out
        if ew.compress_properties:
            out = self.compress_properties(out)
        if ew.remove_duplicates:
            out = self.remove_duplicates(out)
        return out

    # -- persisted rewrite (cleanPersistedPEvents :160-176) ----------------
    def clean_persisted_events(self, ctx,
                               now: Optional[datetime] = None) -> int:
        """Replace the app's stored events with their cleaned form.
        Returns the number of events removed. No-op without a window."""
        if self.event_window is None:
            return 0
        store = ctx.event_store
        app_name = self.app_name or ctx.app_name
        app_id, _ = store.resolve(app_name)
        original = list(store.find(app_name))
        cleaned = self.clean_events(original, now=now)
        keep_ids = {e.event_id for e in cleaned if e.event_id}
        # cleaning only transforms events from `original`, so anything
        # without an id is newly minted (e.g. a compacted $set)
        new_events = [e for e in cleaned if not e.event_id]
        removed = 0
        for e in original:
            if e.event_id and e.event_id not in keep_ids:
                ctx.storage.events().delete(e.event_id, app_id)
                removed += 1
        if new_events:
            ctx.storage.events().insert_batch(
                [e.copy(event_id=None) for e in new_events], app_id)
        log.info("clean_persisted_events: removed %d, wrote %d",
                 removed, len(new_events))
        return removed

    def wipe(self, ctx, new_events: Iterable[Event],
             event_ids_to_remove: Iterable[str]) -> None:
        """Low-level replace (``wipe`` :205-220)."""
        app_name = self.app_name or ctx.app_name
        app_id, _ = ctx.event_store.resolve(app_name)
        ctx.storage.events().insert_batch(
            [e.copy(event_id=None) for e in new_events], app_id)
        for eid in event_ids_to_remove:
            if eid:
                ctx.storage.events().delete(eid, app_id)
