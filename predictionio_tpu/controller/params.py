"""Params system: typed per-controller parameters from engine variant JSON.

Capability parity with the reference's params machinery —
``Params`` marker + ``EngineParams`` (name, params) pairs per controller
(``core/.../controller/EngineParams.scala:35-128``), JSON extraction
(``controller/Engine.scala:355-418``, ``workflow/JsonExtractor.scala:39-140``)
and the reflective ``Doer`` instantiation (``core/AbstractDoer.scala``).

Here params are plain dataclasses; ``instantiate`` replaces reflection with
dataclass-aware construction (a controller class is built from its params
object, or from nothing if it takes none).
"""

from __future__ import annotations

import dataclasses
import inspect
import json
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Type


class Params:
    """Optional marker base for controller params; any dataclass works."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    pass


def params_to_json(params: Any) -> dict:
    """Render a params object to a JSON dict (dataclass fields, or the dict
    itself)."""
    if params is None:
        return {}
    if dataclasses.is_dataclass(params) and not isinstance(params, type):
        return dataclasses.asdict(params)
    if isinstance(params, Mapping):
        return dict(params)
    raise TypeError(f"cannot serialize params of type {type(params)}")


def params_from_json(params_cls: Optional[Type], obj: Mapping[str, Any]) -> Any:
    """Build a params object from a JSON dict. With no declared class, the
    dict passes through (schemaless params, like the reference's gson mode,
    ``workflow/JsonExtractor.scala``)."""
    if params_cls is None:
        return dict(obj)
    if dataclasses.is_dataclass(params_cls):
        # same aliasing as the query boundary: camelCase and python-keyword
        # fields (lambda → lambda_) accepted, unknown keys rejected
        from ..utils.jsonutil import from_jsonable
        try:
            return from_jsonable(params_cls, obj)
        except ValueError as e:
            raise ValueError(
                f"invalid params for {params_cls.__name__}: {e}")
    return params_cls(**obj)


def instantiate(controller_cls: Type, params: Any):
    """Construct a controller from its params — the ``Doer.apply`` role
    (``core/AbstractDoer.scala:35``): prefer a 1-arg (params) constructor,
    fall back to 0-arg."""
    sig = inspect.signature(controller_cls.__init__)
    n_required = sum(
        1 for name, p in sig.parameters.items()
        if name != "self" and p.default is inspect.Parameter.empty
        and p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                       inspect.Parameter.POSITIONAL_ONLY))
    if n_required >= 1:
        return controller_cls(params)
    if params not in (None, {}, EmptyParams()) and len(sig.parameters) > 1:
        return controller_cls(params)
    return controller_cls()


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Named params for every DASE slot
    (``controller/EngineParams.scala:35-128``). ``algorithms`` is a list of
    (name, params) so one engine can train several algorithms at once."""

    datasource: Tuple[str, Any] = ("", None)
    preparator: Tuple[str, Any] = ("", None)
    algorithms: Sequence[Tuple[str, Any]] = ((("", None)),)
    serving: Tuple[str, Any] = ("", None)

    def copy(self, **changes) -> "EngineParams":
        return dataclasses.replace(self, **changes)

    # -- engine.json variant interop ---------------------------------------
    def to_json(self) -> dict:
        def one(pair):
            name, p = pair
            return {"name": name, "params": params_to_json(p)}

        return {
            "dataSourceParams": one(self.datasource),
            "preparatorParams": one(self.preparator),
            "algorithmsParams": [one(a) for a in self.algorithms],
            "servingParams": one(self.serving),
        }


def engine_params_from_variant(
        variant: Mapping[str, Any],
        datasource_params_cls: Optional[Type] = None,
        preparator_params_cls: Optional[Type] = None,
        algorithm_params_classes: Optional[Dict[str, Type]] = None,
        serving_params_cls: Optional[Type] = None) -> EngineParams:
    """Extract :class:`EngineParams` from an ``engine.json``-shaped variant
    (the reference's ``jValueToEngineParams``, ``controller/Engine.scala:355``).

    Accepts both shapes the reference accepts: ``{"params": {...}}`` and
    ``{"name": "...", "params": {...}}`` per slot; ``algorithms`` is a list
    of named entries. Each ``*_cls`` may be a single params class or a
    name → class map (for engines exposing named component variants).
    """

    def one(key, cls) -> Tuple[str, Any]:
        node = variant.get(key)
        if node is None:
            return ("", None)
        name = node.get("name", "")
        if isinstance(cls, Mapping):
            cls = cls.get(name)
        return (name, params_from_json(cls, node.get("params", {})))

    algos: List[Tuple[str, Any]] = []
    for node in variant.get("algorithms", []):
        name = node.get("name", "")
        cls = (algorithm_params_classes or {}).get(name)
        algos.append((name, params_from_json(cls, node.get("params", {}))))

    return EngineParams(
        datasource=one("datasource", datasource_params_cls),
        preparator=one("preparator", preparator_params_cls),
        algorithms=tuple(algos) if algos else ((("", None)),),
        serving=one("serving", serving_params_cls),
    )


def load_variant(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
