"""FastEvalEngine: prefix-memoized hyperparameter evaluation.

Capability parity with ``controller/FastEvalEngine.scala`` (prefix case
classes :52-85, ``getDataSourceResult`` :87-110, ``getPreparatorResult``
:112-130, ``computeAlgorithmsResult`` :132-210, serving+cache plumbing
to :346): when a sweep varies only algorithm params, the DataSource read
and Preparator output are computed once and shared across every variant;
when it varies only serving params, even the per-algorithm
train + batch-predict results are shared.

Cache keys are the JSON rendering of the (name, params) prefix — the
role the reference's case-class equality plays.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Sequence, Tuple

from .context import Context
from .engine import Engine
from .params import EngineParams, params_to_json

log = logging.getLogger(__name__)


def _key(*pairs) -> str:
    """Stable hashable rendering of a params prefix."""
    return json.dumps([[name, params_to_json(p)] for name, p in pairs],
                      sort_keys=True, default=str)


class FastEvalEngineWorkflow:
    """Memoizing evaluator over one engine + one context
    (``FastEvalEngineWorkflow`` object)."""

    def __init__(self, engine: Engine, ctx: Context):
        self.engine = engine
        self.ctx = ctx
        self.datasource_cache: Dict[str, list] = {}
        self.preparator_cache: Dict[str, list] = {}
        self.algorithms_cache: Dict[str, list] = {}
        self.serving_cache: Dict[str, list] = {}
        #: cache-miss counters, keyed like the caches (observability;
        #: asserted on by tests/test_fast_eval_cleaning.py)
        self.miss_counts: Dict[str, int] = {
            "datasource": 0, "preparator": 0, "algorithms": 0, "serving": 0}

    # -- per-prefix computations (FastEvalEngine.scala:87-210) -------------
    def datasource_result(self, ep: EngineParams) -> list:
        key = _key(ep.datasource)
        if key not in self.datasource_cache:
            self.miss_counts["datasource"] += 1
            ds = self.engine.make_datasource(ep)
            self.datasource_cache[key] = list(ds.read_eval(self.ctx))
        return self.datasource_cache[key]

    def preparator_result(self, ep: EngineParams) -> list:
        key = _key(ep.datasource, ep.preparator)
        if key not in self.preparator_cache:
            self.miss_counts["preparator"] += 1
            prep = self.engine.make_preparator(ep)
            folds = self.datasource_result(ep)
            self.preparator_cache[key] = [
                prep.prepare(self.ctx, td) for td, _, _ in folds]
        return self.preparator_cache[key]

    def algorithms_result(self, ep: EngineParams) -> list:
        """Per fold: (supplemented queries, per-query per-algo predictions).

        ``Engine.eval`` supplements queries before prediction
        (``engine.py`` eval loop; ``controller/Engine.scala:767``), so the
        same happens here — and when the Serving class overrides
        ``supplement``, the serving params join the cache key (predictions
        then depend on them; the reference's FastEvalEngine skips
        supplement entirely, which silently diverges from Engine.eval)."""
        from .base import Serving

        serving = self.engine.make_serving(ep)
        supplement_overridden = (
            type(serving).supplement is not Serving.supplement)
        pairs = [ep.datasource, ep.preparator, *ep.algorithms]
        if supplement_overridden:
            pairs.append(ep.serving)
        key = _key(*pairs)
        if key not in self.algorithms_cache:
            self.miss_counts["algorithms"] += 1
            folds = self.datasource_result(ep)
            prepared = self.preparator_result(ep)
            algos = self.engine.make_algorithms(ep)
            per_fold = []
            for (td, ei, qa), pd in zip(folds, prepared):
                queries = [serving.supplement(q) for q, _ in qa]
                per_algo = [a.batch_predict(a.train(self.ctx, pd), queries)
                            for a in algos]
                per_fold.append((queries,
                                 [[preds[i] for preds in per_algo]
                                  for i in range(len(queries))]))
            self.algorithms_cache[key] = per_fold
        return self.algorithms_cache[key]

    def serving_result(self, ep: EngineParams) -> list:
        """Final eval shape: per fold ``(eval_info, [(q, served, a)])``."""
        key = _key(ep.datasource, ep.preparator, *ep.algorithms, ep.serving)
        if key not in self.serving_cache:
            self.miss_counts["serving"] += 1
            folds = self.datasource_result(ep)
            algo_results = self.algorithms_result(ep)
            serving = self.engine.make_serving(ep)
            out = []
            for (td, ei, qa), (queries, fold_preds) in zip(folds,
                                                           algo_results):
                served = [serving.serve(q, preds)
                          for q, preds in zip(queries, fold_preds)]
                out.append((ei, [(q, s, a) for q, s, (_, a)
                                 in zip(queries, served, qa)]))
            self.serving_cache[key] = out
        return self.serving_cache[key]


class FastEvalEngine(Engine):
    """Drop-in Engine whose ``eval``/``batch_eval`` memoize pipeline
    prefixes across engine-params variants. Build from an existing engine:
    ``FastEvalEngine.from_engine(engine)``."""

    @classmethod
    def from_engine(cls, engine: Engine) -> "FastEvalEngine":
        fe = cls.__new__(cls)
        fe.__dict__.update(engine.__dict__)
        return fe

    def workflow_for(self, ctx: Context) -> FastEvalEngineWorkflow:
        """The memoization state for one context. Cached ON the context so
        the fold/prediction data lives exactly as long as the sweep's
        context does — the engine never pins it."""
        cache = getattr(ctx, "_fast_eval_workflows", None)
        if cache is None:
            cache = {}
            object.__setattr__(ctx, "_fast_eval_workflows", cache)
        wf = cache.get(id(self))
        if wf is None:
            wf = FastEvalEngineWorkflow(self, ctx)
            cache[id(self)] = wf
        return wf

    _workflow = workflow_for

    def eval(self, ctx: Context, engine_params: EngineParams) -> list:
        return self._workflow(ctx).serving_result(engine_params)

    def batch_eval(self, ctx: Context,
                   params_list: Sequence[EngineParams]
                   ) -> List[Tuple[EngineParams, list]]:
        wf = self._workflow(ctx)
        out = [(ep, wf.serving_result(ep)) for ep in params_list]
        log.info("FastEvalEngine misses: %s", wf.miss_counts)
        return out
