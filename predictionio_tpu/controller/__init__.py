"""DASE controller API — what engine templates import."""

from .base import (
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    PersistentModelManifest,
    Preparator,
    SanityCheck,
    Serving,
)
from .cleaning import EventWindow, SelfCleaningDataSource
from .context import Context, default_context
from .engine import Engine, EngineFactory, SimpleEngine, TrainResult
from .fast_eval import FastEvalEngine, FastEvalEngineWorkflow
from .persistent import (
    LocalFileSystemPersistentModel,
    PersistentModel,
)
from .evaluation import (
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
    save_best_variant_json,
)
from .metric import (
    AverageMetric,
    Metric,
    OptionAverageMetric,
    OptionStdevMetric,
    PointwiseMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
    ndcg_at_k,
    precision_at_k,
)
from .params import (
    EmptyParams,
    EngineParams,
    Params,
    engine_params_from_variant,
    load_variant,
    params_from_json,
    params_to_json,
)

__all__ = [
    "PersistentModel",
    "LocalFileSystemPersistentModel",
    "FastEvalEngineWorkflow",
    "FastEvalEngine",
    "SelfCleaningDataSource",
    "EventWindow",
    "Algorithm",
    "AverageMetric",
    "AverageServing",
    "Context",
    "DataSource",
    "EmptyParams",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "EngineParamsGenerator",
    "Evaluation",
    "FirstServing",
    "IdentityPreparator",
    "Metric",
    "MetricEvaluator",
    "MetricEvaluatorResult",
    "OptionAverageMetric",
    "OptionStdevMetric",
    "Params",
    "PersistentModelManifest",
    "PointwiseMetric",
    "Preparator",
    "SanityCheck",
    "Serving",
    "SimpleEngine",
    "StdevMetric",
    "SumMetric",
    "TrainResult",
    "ZeroMetric",
    "default_context",
    "engine_params_from_variant",
    "load_variant",
    "ndcg_at_k",
    "params_from_json",
    "params_to_json",
    "precision_at_k",
    "save_best_variant_json",
]
