"""Workflow context: what flows through every DASE stage.

The TPU-native replacement for the SparkContext the reference threads
through ``BaseDataSource.readTrainingBase(sc)`` etc.
(``core/BaseDataSource.scala:43``, ``workflow/WorkflowContext.scala``):
a :class:`Context` carries the device mesh, the PRNG seed, storage access,
and workflow options. Controllers receive it everywhere the reference
passed ``sc``.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh

from ..data.storage.registry import Storage, get_storage
from ..data.store import EventStoreFacade
from ..parallel.mesh import make_mesh


@dataclass
class Context:
    """Execution context for train/eval/serve.

    ``mesh`` is the device mesh all sharded computation lays out over —
    mesh of 1 device ≡ the reference's L(local) mode, mesh of N ≡ P mode;
    one API for both (SURVEY §2.3).
    """

    mesh: Optional[Mesh] = None
    seed: int = 0
    app_name: str = ""
    batch: str = ""
    verbose: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    skip_sanity_check: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)
    #: per-stage wall-clock seconds, filled by the workflow as it runs
    #: (read_s / prepare_s / algo_train_s / persist_s ...) — the train
    #: log's stage breakdown (VERDICT r4: the flagship number was host-
    #: bound with no evidence of where the host seconds went)
    stage_timings: Dict[str, float] = field(default_factory=dict)
    _storage: Optional[Storage] = None

    @property
    def storage(self) -> Storage:
        return self._storage if self._storage is not None else get_storage()

    @property
    def event_store(self) -> EventStoreFacade:
        return EventStoreFacade(self._storage)

    def rng(self) -> jax.Array:
        return jax.random.key(self.seed)

    def with_mesh(self) -> Mesh:
        """The mesh, defaulting to all local devices on the data axis."""
        if self.mesh is None:
            self.mesh = make_mesh()
        return self.mesh

    def copy(self, **changes) -> "Context":
        return replace(self, **changes)


def default_context(**kw) -> Context:
    return Context(**kw)
