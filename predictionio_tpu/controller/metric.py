"""Metric library.

Capability parity with ``controller/Metric.scala`` (base ``Metric`` with
Ordering-based ``compare`` :39-57; ``AverageMetric`` :99,
``OptionAverageMetric`` :124, ``StdevMetric`` :151, ``OptionStdevMetric``
:179, ``SumMetric`` :205, ``ZeroMetric`` :234). Evaluation data is
``[(eval_info, [(q, p, a)])]`` — the host-side analogue of the reference's
``Seq[(EI, RDD[(Q,P,A)])]``; per-point scores aggregate with numpy (the
``StatsCounter`` union role, :60-96).
"""

from __future__ import annotations

import abc
import math
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

import numpy as np

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")

EvalData = Sequence[Tuple[EI, Sequence[Tuple[Q, P, A]]]]


class Metric(abc.ABC, Generic[EI, Q, P, A]):
    """Computes a scalar score from evaluation output; larger is better
    unless ``compare`` is overridden."""

    @abc.abstractmethod
    def calculate(self, eval_data: EvalData) -> float:
        ...

    def compare(self, a: float, b: float) -> int:
        """Ordering for model selection (>0 ⇒ a better)."""
        return (a > b) - (a < b)

    @property
    def header(self) -> str:
        return type(self).__name__

    def __repr__(self) -> str:
        return self.header


class PointwiseMetric(Metric[EI, Q, P, A]):
    """Base for metrics defined by a per-(q,p,a) score."""

    def calculate_point(self, eval_info: EI, q: Q, p: P, a: A
                        ) -> Optional[float]:
        raise NotImplementedError

    def _scores(self, eval_data: EvalData) -> np.ndarray:
        vals: List[float] = []
        for ei, qpas in eval_data:
            for q, p, a in qpas:
                s = self.calculate_point(ei, q, p, a)
                if s is not None:
                    vals.append(float(s))
        return np.asarray(vals, dtype=np.float64)


class AverageMetric(PointwiseMetric):
    """Mean of per-point scores (``Metric.scala:99``). Subclasses returning
    None from ``calculate_point`` get Option semantics (:124): None points
    are excluded from the denominator."""

    def calculate(self, eval_data: EvalData) -> float:
        s = self._scores(eval_data)
        return float(s.mean()) if s.size else float("nan")


OptionAverageMetric = AverageMetric


class StdevMetric(PointwiseMetric):
    """Population stdev of per-point scores (``Metric.scala:151,179``)."""

    def calculate(self, eval_data: EvalData) -> float:
        s = self._scores(eval_data)
        return float(s.std()) if s.size else float("nan")


OptionStdevMetric = StdevMetric


class SumMetric(PointwiseMetric):
    """Sum of per-point scores (``Metric.scala:205``)."""

    def calculate(self, eval_data: EvalData) -> float:
        return float(self._scores(eval_data).sum())


class ZeroMetric(Metric):
    """Always 0 (``Metric.scala:234``) — placeholder for eval-only runs."""

    def calculate(self, eval_data: EvalData) -> float:
        return 0.0


# -- ranking metrics (the quality targets in BASELINE.md) -------------------

def precision_at_k(predicted: Sequence[Any], relevant: set, k: int) -> Optional[float]:
    """Precision@K as the reference's recommendation template computes it
    (``tests/pio_tests/engines/recommendation-engine/src/main/scala/
    Evaluation.scala:32-51``): |top-k ∩ relevant| / min(k, |relevant|);
    None (excluded) when there are no relevant items."""
    if not relevant:
        return None
    topk = list(predicted)[:k]
    hits = sum(1 for x in topk if x in relevant)
    return hits / min(k, len(relevant))


def ndcg_at_k(predicted: Sequence[Any], relevant: set, k: int) -> Optional[float]:
    """Binary-relevance NDCG@K — the BASELINE.md target metric."""
    if not relevant:
        return None
    topk = list(predicted)[:k]
    dcg = sum(1.0 / math.log2(i + 2) for i, x in enumerate(topk)
              if x in relevant)
    ideal = sum(1.0 / math.log2(i + 2)
                for i in range(min(k, len(relevant))))
    return dcg / ideal if ideal > 0 else None
