"""Native (C++) hot-path components, with graceful fallback.

The framework's compute plane is JAX/XLA; the RUNTIME around it uses
native code where Python is the measured bottleneck — first component:
the jsonl→columnar segment codec (``_codec.cpp``), covering the role
the reference's JVM/parser stack played for its storage codecs.

The extension is compiled on first use with the toolchain's ``g++``
(one ``-O2 -shared -fPIC`` invocation against this interpreter's
headers, cached per source digest under ``~/.cache/predictionio_tpu``)
— or import a prebuilt ``_codec`` if packaging built one. Every caller
falls back to the pure-Python path when no compiler/extension is
available, so native code is an accelerator, never a dependency.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
from typing import Optional

log = logging.getLogger(__name__)

_state: dict = {}


def _build(src: str) -> Optional[object]:
    try:
        cache = os.path.join(
            os.environ.get("XDG_CACHE_HOME",
                           os.path.expanduser("~/.cache")),
            "predictionio_tpu")
        os.makedirs(cache, exist_ok=True)
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError as e:
        # e.g. an installed wheel without the .cpp, or unwritable cache
        log.info("native codec source unavailable (%s); using the "
                 "pure-Python path", e)
        return None
    # tag carries python version AND platform: a shared home across
    # heterogeneous hosts must not serve one arch's .so to another
    plat = sysconfig.get_platform().replace("-", "_")
    tag = (f"_codec-{digest}-cp{sys.version_info.major}"
           f"{sys.version_info.minor}-{plat}.so")
    out = os.path.join(cache, tag)
    if not os.path.exists(out):
        # unique tmp name: concurrent first-use builds (multi-host
        # training on a shared home — pid alone collides ACROSS hosts)
        # must not interleave into one file
        import uuid as _uuid
        tmp = f"{out}.tmp.{_uuid.uuid4().hex}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               f"-I{sysconfig.get_paths()['include']}", src,
               "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, out)
        except (OSError, subprocess.SubprocessError) as e:
            try:  # a failed/timed-out build must not leak its tmp
                os.unlink(tmp)
            except OSError:
                pass
            log.info("native codec build unavailable (%s); using the "
                     "pure-Python path", e)
            return None
    spec = importlib.util.spec_from_file_location(
        "predictionio_tpu.native._codec", out)
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except Exception as e:  # noqa: BLE001 — ABI mismatch etc.
        log.info("native codec load failed (%s); using the pure-Python "
                 "path", e)
        return None
    return mod


def codec() -> Optional[object]:
    """The ``_codec`` extension module, or None (pure-Python fallback).
    Tried once per process; set ``PTPU_NO_NATIVE=1`` to disable."""
    if "codec" in _state:
        return _state["codec"]
    mod = None
    if os.environ.get("PTPU_NO_NATIVE") != "1":
        try:
            from . import _codec as mod  # type: ignore[attr-defined]
        except ImportError:
            mod = _build(os.path.join(os.path.dirname(__file__),
                                      "_codec.cpp"))
    _state["codec"] = mod
    return mod
