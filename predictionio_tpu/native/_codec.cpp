// predictionio_tpu native columnar codec.
//
// The segmentfs event log is JSONL the framework itself writes
// ({"op":"put","event":{...}} / {"op":"del","id":...}); its columnar
// sidecar encode was measured parse-bound (~54k events/s through
// json.loads + dict access on one core). This module parses one whole
// segment buffer in C++ — a full JSON tokenizer (string escapes incl.
// \uXXXX surrogate pairs, nested values) with shallow extraction of the
// bulk-projection fields — and returns plain Python lists ready for the
// existing columnar_from_columns path. Any non-"put" record makes the
// parse return None (the Python caller already rebuilds on deletes).
//
// Build: auto-compiled on first use by predictionio_tpu/native
// (g++ -O2 -shared -fPIC), or `python setup_native.py build_ext`.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <clocale>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <vector>

namespace {

// strtod is locale-dependent (an LC_NUMERIC with a decimal comma would
// misparse "4.5"); parse with a pinned C locale instead.
locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", nullptr);
  return loc;
}

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const char* s, Py_ssize_t n) : p(s), end(s + n) {}

  void fail() { ok = false; }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  }

  bool expect(char c) {
    skip_ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    fail();
    return false;
  }

  bool peek(char c) {
    skip_ws();
    return p < end && *p == c;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out.push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  int hex4() {
    if (end - p < 4) {
      fail();
      return -1;
    }
    int v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else {
        fail();
        return -1;
      }
    }
    p += 4;
    return v;
  }

  // Parse a JSON string (opening quote already expected by caller via
  // expect('"') == false; here we do the full job).
  bool parse_string(std::string& out) {
    out.clear();
    if (!expect('"')) return false;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (p >= end) {
          fail();
          return false;
        }
        char e = *p++;
        switch (e) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'n': out.push_back('\n'); break;
          case 'r': out.push_back('\r'); break;
          case 't': out.push_back('\t'); break;
          case 'u': {
            int u = hex4();
            if (!ok) return false;
            unsigned cp = static_cast<unsigned>(u);
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              // must be a valid surrogate pair; a LONE surrogate (legal
              // to Python's json) has no UTF-8 form — fail so the
              // caller falls back to the Python parser
              if (end - p >= 6 && p[0] == '\\' && p[1] == 'u') {
                p += 2;
                int lo = hex4();
                if (!ok) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else {
                  fail();
                  return false;
                }
              } else {
                fail();
                return false;
              }
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              fail();  // lone low surrogate
              return false;
            }
            append_utf8(out, cp);
            break;
          }
          default:
            fail();
            return false;
        }
      } else {
        out.push_back(c);
      }
    }
    fail();
    return false;
  }

  bool skip_string() {
    if (!expect('"')) return false;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (p >= end) break;
        ++p;
      }
    }
    fail();
    return false;
  }

  bool parse_number(double* out) {
    skip_ws();
    char* endptr = nullptr;
    double v = strtod_l(p, &endptr, c_locale());
    if (endptr == p) {
      fail();
      return false;
    }
    p = endptr;
    if (out) *out = v;
    return true;
  }

  bool skip_value();

  bool skip_object() {
    if (!expect('{')) return false;
    if (peek('}')) {
      ++p;
      return true;
    }
    while (ok) {
      if (!skip_string()) return false;
      if (!expect(':')) return false;
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      return expect('}');
    }
    return false;
  }

  bool skip_array() {
    if (!expect('[')) return false;
    if (peek(']')) {
      ++p;
      return true;
    }
    while (ok) {
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      return expect(']');
    }
    return false;
  }

  bool skip_literal(const char* lit, size_t n) {
    if (static_cast<size_t>(end - p) < n || memcmp(p, lit, n) != 0) {
      fail();
      return false;
    }
    p += n;
    return true;
  }
};

bool Parser::skip_value() {
  skip_ws();
  if (p >= end) {
    fail();
    return false;
  }
  switch (*p) {
    case '"': return skip_string();
    case '{': return skip_object();
    case '[': return skip_array();
    case 't': return skip_literal("true", 4);
    case 'f': return skip_literal("false", 5);
    case 'n': return skip_literal("null", 4);
    default: return parse_number(nullptr);
  }
}

struct Record {
  std::string event, entity_type, entity_id, event_time, event_id;
  std::string target_type, target_id;
  bool has_tt = false, has_ti = false;
  const char* props_start = nullptr;
  const char* props_end = nullptr;
  std::vector<double> fprops;  // parallel to requested names
};

// events-object parser with shallow float-prop extraction
bool parse_event_obj(Parser& ps, Record& rec,
                     const std::vector<std::string>& want) {
  if (!ps.expect('{')) return false;
  rec.fprops.assign(want.size(), NAN);
  if (ps.peek('}')) {
    ++ps.p;
    return true;
  }
  std::string key;
  while (ps.ok) {
    if (!ps.parse_string(key)) return false;
    if (!ps.expect(':')) return false;
    if (key == "event") {
      if (!ps.parse_string(rec.event)) return false;
    } else if (key == "entityType") {
      if (!ps.parse_string(rec.entity_type)) return false;
    } else if (key == "entityId") {
      if (!ps.parse_string(rec.entity_id)) return false;
    } else if (key == "targetEntityType") {
      if (!ps.parse_string(rec.target_type)) return false;
      rec.has_tt = true;
    } else if (key == "targetEntityId") {
      if (!ps.parse_string(rec.target_id)) return false;
      rec.has_ti = true;
    } else if (key == "eventTime") {
      if (!ps.parse_string(rec.event_time)) return false;
    } else if (key == "eventId") {
      if (!ps.parse_string(rec.event_id)) return false;
    } else if (key == "properties") {
      ps.skip_ws();
      rec.props_start = ps.p;
      if (ps.peek('{')) {
        // shallow walk: capture requested numeric props, skip the rest
        ++ps.p;
        if (ps.peek('}')) {
          ++ps.p;
        } else {
          std::string pk;
          while (ps.ok) {
            if (!ps.parse_string(pk)) return false;
            if (!ps.expect(':')) return false;
            ps.skip_ws();
            bool taken = false;
            for (size_t w = 0; w < want.size(); ++w) {
              if (pk == want[w]) {
                // numbers only — bools/strings/null stay NaN.
                // Python's json also emits/accepts the non-standard
                // Infinity/-Infinity/NaN tokens: match it (strtod
                // parses them), else the two paths diverge on inf.
                if (ps.p < ps.end &&
                    (*ps.p == '-' || (*ps.p >= '0' && *ps.p <= '9') ||
                     *ps.p == 'I' || *ps.p == 'N')) {
                  double v;
                  if (!ps.parse_number(&v)) return false;
                  rec.fprops[w] = v;
                } else {
                  if (!ps.skip_value()) return false;
                }
                taken = true;
                break;
              }
            }
            if (!taken && !ps.skip_value()) return false;
            ps.skip_ws();
            if (ps.p < ps.end && *ps.p == ',') {
              ++ps.p;
              continue;
            }
            if (!ps.expect('}')) return false;
            break;
          }
          if (!ps.ok) return false;
        }
      } else {
        if (!ps.skip_value()) return false;
      }
      rec.props_end = ps.p;
    } else {
      if (!ps.skip_value()) return false;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') {
      ++ps.p;
      continue;
    }
    return ps.expect('}');
  }
  return false;
}

PyObject* str_or_die(const std::string& s) {
  return PyUnicode_FromStringAndSize(s.data(),
                                     static_cast<Py_ssize_t>(s.size()));
}

// parse_segment(data: bytes, float_props: tuple[str, ...])
//   -> None                      (a non-"put" record: caller rebuilds)
//    | (event, entity_type, entity_id, target_type, target_id,
//       event_time, event_id, props_raw, fprops_lists)  all lists
PyObject* parse_segment(PyObject*, PyObject* args) {
  const char* buf;
  Py_ssize_t len;
  PyObject* want_tuple;
  if (!PyArg_ParseTuple(args, "y#O!", &buf, &len, &PyTuple_Type,
                        &want_tuple))
    return nullptr;
  std::vector<std::string> want;
  for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(want_tuple); ++i) {
    PyObject* it = PyTuple_GET_ITEM(want_tuple, i);
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(it, &n);
    if (!s) return nullptr;
    want.emplace_back(s, static_cast<size_t>(n));
  }

  std::vector<Record> recs;
  recs.reserve(1024);
  const char* line = buf;
  const char* bend = buf + len;
  std::string key, op, del_id;
  while (line < bend) {
    const char* nl = static_cast<const char*>(
        memchr(line, '\n', static_cast<size_t>(bend - line)));
    const char* lend = nl ? nl : bend;
    bool blank = true;
    for (const char* q = line; q < lend; ++q)
      if (*q != ' ' && *q != '\t' && *q != '\r') {
        blank = false;
        break;
      }
    if (blank) {
      line = nl ? nl + 1 : bend;
      continue;
    }
    Parser ps(line, lend - line);
    Record rec;
    bool got_event = false;
    op.clear();
    if (!ps.expect('{')) goto bad;
    while (ps.ok) {
      if (!ps.parse_string(key)) goto bad;
      if (!ps.expect(':')) goto bad;
      if (key == "op") {
        if (!ps.parse_string(op)) goto bad;
      } else if (key == "event") {
        if (!parse_event_obj(ps, rec, want)) goto bad;
        got_event = true;
      } else if (key == "id") {
        if (!ps.parse_string(del_id)) goto bad;
      } else {
        if (!ps.skip_value()) goto bad;
      }
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == ',') {
        ++ps.p;
        continue;
      }
      if (!ps.expect('}')) goto bad;
      break;
    }
    if (!ps.ok) goto bad;
    if (op != "put") Py_RETURN_NONE;  // deletes: Python path rebuilds
    if (!got_event || rec.event.empty() || rec.entity_type.empty())
      goto bad;
    recs.push_back(std::move(rec));
    line = nl ? nl + 1 : bend;
    continue;
  bad:
    PyErr_Format(PyExc_ValueError,
                 "native codec: malformed segment line at offset %zd",
                 static_cast<Py_ssize_t>(line - buf));
    return nullptr;
  }

  Py_ssize_t n = static_cast<Py_ssize_t>(recs.size());
  PyObject* out = PyTuple_New(9);
  if (!out) return nullptr;
  PyObject* cols[8];
  for (int c = 0; c < 8; ++c) {
    cols[c] = PyList_New(n);
    if (!cols[c]) {
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(out, c, cols[c]);
  }
  PyObject* fcols = PyList_New(static_cast<Py_ssize_t>(want.size()));
  if (!fcols) {
    Py_DECREF(out);
    return nullptr;
  }
  PyTuple_SET_ITEM(out, 8, fcols);
  std::vector<PyObject*> flists(want.size());
  for (size_t w = 0; w < want.size(); ++w) {
    flists[w] = PyList_New(n);
    if (!flists[w]) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(fcols, static_cast<Py_ssize_t>(w), flists[w]);
  }

  for (Py_ssize_t i = 0; i < n; ++i) {
    Record& r = recs[static_cast<size_t>(i)];
    PyObject* v;
    if (!(v = str_or_die(r.event))) goto fail;
    PyList_SET_ITEM(cols[0], i, v);
    if (!(v = str_or_die(r.entity_type))) goto fail;
    PyList_SET_ITEM(cols[1], i, v);
    if (!(v = str_or_die(r.entity_id))) goto fail;
    PyList_SET_ITEM(cols[2], i, v);
    if (r.has_tt) {
      if (!(v = str_or_die(r.target_type))) goto fail;
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    PyList_SET_ITEM(cols[3], i, v);
    if (r.has_ti) {
      if (!(v = str_or_die(r.target_id))) goto fail;
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    PyList_SET_ITEM(cols[4], i, v);
    if (!(v = str_or_die(r.event_time))) goto fail;
    PyList_SET_ITEM(cols[5], i, v);
    if (!(v = str_or_die(r.event_id))) goto fail;
    PyList_SET_ITEM(cols[6], i, v);
    if (r.props_start && r.props_end > r.props_start) {
      v = PyBytes_FromStringAndSize(
          r.props_start,
          static_cast<Py_ssize_t>(r.props_end - r.props_start));
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    if (!v) goto fail;
    PyList_SET_ITEM(cols[7], i, v);
    for (size_t w = 0; w < want.size(); ++w) {
      v = PyFloat_FromDouble(r.fprops[w]);
      if (!v) goto fail;
      PyList_SET_ITEM(flists[w], i, v);
    }
    continue;
  fail:
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

// ---------------------------------------------------------------------
// Bulk import lane: API-format JSONL -> segment payload, one C++ pass.
//
// `ptpu import` was measured at ~12k events/s/core through the Python
// pipeline (json.loads -> Event.from_json -> to_json -> json.dumps,
// each about a third of the cost). This converts a whole chunk of
// API-JSON lines straight into the segmentfs record format
// ({"op": "put", "event": {...}}), validating the reference's event
// rules (Event.scala:112-160 parity, same checks as
// data/event.py:validate_event) and normalizing timestamps to the
// framework's canonical isoformat-millis wire form. Anything this
// strict lane can't prove it handles EXACTLY like the Python path
// (exotic ISO forms, lone surrogates, non-string optional fields,
// validation failures that must raise the canonical message) makes the
// whole chunk fall back to the Python lane — the fast path never
// guesses.

long long days_from_civil(long long y, unsigned m, unsigned d) {
  // Howard Hinnant's civil-days algorithm (public domain).
  y -= m <= 2;
  const long long era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + static_cast<long long>(doe) - 719468;
}

void civil_from_days(long long z, long long* yy, unsigned* mm,
                     unsigned* dd) {
  z += 719468;
  const long long era = (z >= 0 ? z : z - 146096) / 146097;
  const unsigned doe = static_cast<unsigned>(z - era * 146097);
  const unsigned yoe =
      (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  const long long y = static_cast<long long>(yoe) + era * 400;
  const unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  const unsigned mp = (5 * doy + 2) / 153;
  const unsigned d = doy - (153 * mp + 2) / 5 + 1;
  const unsigned m = mp < 10 ? mp + 3 : mp - 9;
  *yy = y + (m <= 2);
  *mm = m;
  *dd = d;
}

int days_in_month(int y, int m) {
  static const int dm[] = {31, 28, 31, 30, 31, 30,
                           31, 31, 30, 31, 30, 31};
  if (m == 2 && (y % 4 == 0 && (y % 100 != 0 || y % 400 == 0)))
    return 29;
  return dm[m - 1];
}

bool ndigits(const char*& p, const char* end, int n, int* out) {
  if (end - p < n) return false;
  int v = 0;
  for (int i = 0; i < n; ++i) {
    if (p[i] < '0' || p[i] > '9') return false;
    v = v * 10 + (p[i] - '0');
  }
  p += n;
  *out = v;
  return true;
}

// Strict ISO-8601 subset -> epoch millis UTC. Covers the framework's
// own wire form plus the common offset spellings; anything else
// returns false and the chunk takes the Python lane (whose
// datetime.fromisoformat accepts more). Fraction truncates to millis,
// matching isoformat_millis (microsecond // 1000).
bool parse_iso_millis(const std::string& s, long long* out_ms) {
  const char* p = s.c_str();
  const char* end = p + s.size();
  int y, mo, d;
  if (!ndigits(p, end, 4, &y)) return false;
  if (p >= end || *p != '-') return false;
  ++p;
  if (!ndigits(p, end, 2, &mo)) return false;
  if (p >= end || *p != '-') return false;
  ++p;
  if (!ndigits(p, end, 2, &d)) return false;
  if (y < 1 || mo < 1 || mo > 12 || d < 1 || d > days_in_month(y, mo))
    return false;  // Python's datetime is bounded to years 1..9999
  int hh = 0, mi = 0, ss = 0, ms = 0;
  int off_h = 0, off_m = 0, off_s = 0;
  bool neg_off = false;
  if (p < end) {
    if (*p != 'T' && *p != 't' && *p != ' ') return false;
    ++p;
    if (!ndigits(p, end, 2, &hh)) return false;
    if (p < end && *p == ':') {
      ++p;
      if (!ndigits(p, end, 2, &mi)) return false;
      if (p < end && *p == ':') {
        ++p;
        if (!ndigits(p, end, 2, &ss)) return false;
        if (p < end && *p == '.') {
          ++p;
          int nd = 0;
          long frac = 0;
          while (p < end && *p >= '0' && *p <= '9') {
            if (nd < 3) {
              frac = frac * 10 + (*p - '0');
              ++nd;
            }
            ++p;
          }
          if (nd == 0) return false;
          while (nd < 3) {
            frac *= 10;
            ++nd;
          }
          ms = static_cast<int>(frac);
        }
      }
    }
    if (hh > 23 || mi > 59 || ss > 59) return false;
    if (p < end) {
      char c = *p;
      if (c == 'Z' || c == 'z') {
        ++p;
      } else if (c == '+' || c == '-') {
        neg_off = (c == '-');
        ++p;
        if (!ndigits(p, end, 2, &off_h)) return false;
        if (p < end && *p == ':') {
          ++p;
          if (!ndigits(p, end, 2, &off_m)) return false;
          if (p < end && *p == ':') {
            ++p;
            if (!ndigits(p, end, 2, &off_s)) return false;
          }
        } else if (p < end && *p >= '0' && *p <= '9') {
          if (!ndigits(p, end, 2, &off_m)) return false;
        }
      } else {
        return false;
      }
    }
  }
  if (p != end) return false;
  if (off_h > 23 || off_m > 59 || off_s > 59)
    return false;  // fromisoformat rejects offsets >= 24h
  long long secs = days_from_civil(y, static_cast<unsigned>(mo),
                                   static_cast<unsigned>(d)) * 86400LL +
                   hh * 3600LL + mi * 60LL + ss;
  long long off = off_h * 3600LL + off_m * 60LL + off_s;
  secs -= neg_off ? -off : off;
  *out_ms = secs * 1000 + ms;
  // the offset shift must not cross Python's year 1..9999 bounds —
  // the Python lane raises (astimezone OverflowError) and fails the
  // import cleanly; publishing such a timestamp would poison every
  // subsequent replay of the log
  static const long long kMinMs = days_from_civil(1, 1, 1) * 86400000LL;
  static const long long kMaxMs =
      (days_from_civil(9999, 12, 31) + 1) * 86400000LL - 1;
  return *out_ms >= kMinMs && *out_ms <= kMaxMs;
}

void emit_iso_millis(long long ms, std::string& out) {
  long long secs = ms / 1000;
  int milli = static_cast<int>(ms % 1000);
  if (milli < 0) {
    milli += 1000;
    secs -= 1;
  }
  long long days = secs / 86400;
  long long rem = secs % 86400;
  if (rem < 0) {
    rem += 86400;
    days -= 1;
  }
  long long y;
  unsigned mo, d;
  civil_from_days(days, &y, &mo, &d);
  char buf[48];
  snprintf(buf, sizeof buf,
           "%04lld-%02u-%02uT%02lld:%02lld:%02lld.%03dZ", y, mo, d,
           rem / 3600, (rem % 3600) / 60, rem % 60, milli);
  out += buf;
}

void emit_json_string(std::string& out, const char* s, size_t n) {
  out.push_back('"');
  for (size_t i = 0; i < n; ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char b[8];
          snprintf(b, sizeof b, "\\u%04x", c);
          out += b;
        } else {
          out.push_back(static_cast<char>(c));  // raw UTF-8 is fine
        }
    }
  }
  out.push_back('"');
}

bool reserved_name(const std::string& s) {
  return (!s.empty() && s[0] == '$') || s.rfind("pio_", 0) == 0;
}

struct ImpRec {
  std::string event, etype, eid, evid, etime, ctime;
  std::string ttype, tid;
  bool has_tt = false, has_ti = false;
  bool has_evid = false, has_etime = false, has_ctime = false;
  const char* props_b = nullptr;
  const char* props_e = nullptr;
  size_t props_n = 0;
  const char* tags_b = nullptr;
  const char* tags_e = nullptr;
  bool tags_nonempty = false;
  const char* prid_b = nullptr;
  const char* prid_e = nullptr;
  bool has_prid = false;
};

// string -> 0, null -> 1, anything else -> -1 (Python lane decides)
int parse_str_or_null(Parser& ps, std::string& out) {
  ps.skip_ws();
  if (ps.p < ps.end && *ps.p == 'n')
    return ps.skip_literal("null", 4) ? 1 : -1;
  return ps.parse_string(out) ? 0 : -1;
}

bool parse_import_event(Parser& ps, ImpRec& r) {
  if (!ps.expect('{')) return false;
  if (ps.peek('}')) {
    ++ps.p;
    return true;  // required-field validation rejects it below
  }
  std::string key, pk;
  while (ps.ok) {
    if (!ps.parse_string(key)) return false;
    if (!ps.expect(':')) return false;
    if (key == "event") {
      if (!ps.parse_string(r.event)) return false;
    } else if (key == "entityType") {
      if (!ps.parse_string(r.etype)) return false;
    } else if (key == "entityId") {
      if (!ps.parse_string(r.eid)) return false;
    } else if (key == "eventId") {
      int k = parse_str_or_null(ps, r.evid);
      if (k < 0) return false;
      // empty/None both mean "assign fresh" (`e.event_id or uuid4`)
      r.has_evid = (k == 0 && !r.evid.empty());
    } else if (key == "targetEntityType") {
      int k = parse_str_or_null(ps, r.ttype);
      if (k < 0) return false;
      r.has_tt = (k == 0);
    } else if (key == "targetEntityId") {
      int k = parse_str_or_null(ps, r.tid);
      if (k < 0) return false;
      r.has_ti = (k == 0);
    } else if (key == "eventTime") {
      int k = parse_str_or_null(ps, r.etime);
      if (k < 0) return false;
      r.has_etime = (k == 0);  // JSON null -> default now, like Python
    } else if (key == "creationTime") {
      int k = parse_str_or_null(ps, r.ctime);
      if (k < 0) return false;
      r.has_ctime = (k == 0);
    } else if (key == "prId") {
      ps.skip_ws();
      const char* b = ps.p;
      if (ps.end - ps.p >= 4 && memcmp(ps.p, "null", 4) == 0) {
        ps.p += 4;
        r.has_prid = false;
      } else {
        if (!ps.skip_value()) return false;
        r.prid_b = b;
        r.prid_e = ps.p;
        r.has_prid = true;
      }
    } else if (key == "properties") {
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == 'n') {
        if (!ps.skip_literal("null", 4)) return false;
        r.props_b = r.props_e = nullptr;
        r.props_n = 0;
      } else {
        const char* b = ps.p;
        if (!ps.expect('{')) return false;  // non-object props: Python
        r.props_n = 0;
        if (ps.peek('}')) {
          ++ps.p;
        } else {
          while (ps.ok) {
            if (!ps.parse_string(pk)) return false;
            if (reserved_name(pk)) return false;  // canonical error
            if (!ps.expect(':')) return false;
            if (!ps.skip_value()) return false;
            ++r.props_n;
            ps.skip_ws();
            if (ps.p < ps.end && *ps.p == ',') {
              ++ps.p;
              continue;
            }
            if (!ps.expect('}')) return false;
            break;
          }
          if (!ps.ok) return false;
        }
        r.props_b = b;
        r.props_e = ps.p;
      }
    } else if (key == "tags") {
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == 'n') {
        if (!ps.skip_literal("null", 4)) return false;
        r.tags_b = r.tags_e = nullptr;
        r.tags_nonempty = false;
      } else {
        const char* b = ps.p;
        if (ps.p >= ps.end || *ps.p != '[') return false;  // Python lane
        const char* q = ps.p + 1;
        while (q < ps.end && (*q == ' ' || *q == '\t' || *q == '\r'))
          ++q;
        bool empty = (q < ps.end && *q == ']');
        if (!ps.skip_array()) return false;
        r.tags_b = b;
        r.tags_e = ps.p;
        r.tags_nonempty = !empty;
      }
    } else {
      if (!ps.skip_value()) return false;  // unknown keys are dropped
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') {
      ++ps.p;
      continue;
    }
    return ps.expect('}');
  }
  return false;
}

// validate_event parity (data/event.py:179, Event.scala:112-160).
// false -> Python lane raises the canonical EventValidationError.
bool validate_imp(const ImpRec& r) {
  if (r.event.empty() || r.etype.empty() || r.eid.empty()) return false;
  if (r.has_tt && r.ttype.empty()) return false;
  if (r.has_ti && r.tid.empty()) return false;
  if (r.has_tt != r.has_ti) return false;
  const bool special = r.event == "$set" || r.event == "$unset" ||
                       r.event == "$delete";
  if (reserved_name(r.event) && !special) return false;
  if (r.event == "$unset" && r.props_n == 0) return false;
  if (special && (r.has_tt || r.has_ti)) return false;
  if (reserved_name(r.etype) && r.etype != "pio_pr") return false;
  if (r.has_tt && reserved_name(r.ttype) && r.ttype != "pio_pr")
    return false;
  return true;
}

// import_jsonl(data: bytes, rand: bytes, now_iso: str)
//   -> (payload: bytes, n_events: int, 0)   whole chunk converted
//    | (None, 0, bad_line: int)             1-based line that needs the
//      Python lane; the caller re-runs the ENTIRE chunk there so
//      ordering and error messages match the pure-Python path exactly.
// `rand` supplies >=16 bytes per line needing a fresh event id
// (os.urandom upstream); ids get uuid4 version/variant bits.
PyObject* import_jsonl(PyObject*, PyObject* args) {
  const char* buf;
  Py_ssize_t len;
  const char* rand;
  Py_ssize_t rand_len;
  const char* now;
  Py_ssize_t now_len;
  if (!PyArg_ParseTuple(args, "y#y#s#", &buf, &len, &rand, &rand_len,
                        &now, &now_len))
    return nullptr;
  std::string payload;
  payload.reserve(static_cast<size_t>(len) +
                  static_cast<size_t>(len) / 2 + 4096);
  const std::string now_s(now, static_cast<size_t>(now_len));
  Py_ssize_t rand_off = 0;
  long long nline = 0, nev = 0;
  const char* line = buf;
  const char* bend = buf + len;
  char idbuf[33];
  static const char hexd[] = "0123456789abcdef";
  std::string et, ct;
  // the parse/encode loop touches only borrowed immutable buffers
  // (kept alive by the args tuple) and C++ state, so the GIL is
  // released for the duration — a 32MB server-side block otherwise
  // stalls every other storage-server thread (ADVICE r4)
  bool fellback = false, rand_exhausted = false;
  Py_BEGIN_ALLOW_THREADS;
  while (line < bend) {
    ++nline;
    const char* nl = static_cast<const char*>(
        memchr(line, '\n', static_cast<size_t>(bend - line)));
    const char* lend = nl ? nl : bend;
    const char* lb = line;
    const char* le = lend;
    while (lb < le && (*lb == ' ' || *lb == '\t' || *lb == '\r')) ++lb;
    while (le > lb &&
           (le[-1] == ' ' || le[-1] == '\t' || le[-1] == '\r'))
      --le;
    line = nl ? nl + 1 : bend;
    if (lb == le) continue;
    Parser ps(lb, le - lb);
    ImpRec r;
    if (!parse_import_event(ps, r)) goto fallback;
    ps.skip_ws();
    if (ps.p != ps.end) goto fallback;  // trailing garbage on the line
    if (!validate_imp(r)) goto fallback;
    {
      long long tms;
      et.clear();
      ct.clear();
      if (r.has_etime) {
        if (!parse_iso_millis(r.etime, &tms)) goto fallback;
        emit_iso_millis(tms, et);
      } else {
        et = now_s;
      }
      if (r.has_ctime) {
        if (!parse_iso_millis(r.ctime, &tms)) goto fallback;
        emit_iso_millis(tms, ct);
      } else {
        ct = now_s;
      }
      const char* id = idbuf;
      size_t idn = 32;
      if (r.has_evid) {
        id = r.evid.data();
        idn = r.evid.size();
      } else {
        if (rand_off + 16 > rand_len) {
          rand_exhausted = true;
          goto loop_done;
        }
        unsigned char b[16];
        memcpy(b, rand + rand_off, 16);
        rand_off += 16;
        b[6] = (b[6] & 0x0f) | 0x40;  // uuid4 version
        b[8] = (b[8] & 0x3f) | 0x80;  // RFC 4122 variant
        for (int i = 0; i < 16; ++i) {
          idbuf[2 * i] = hexd[b[i] >> 4];
          idbuf[2 * i + 1] = hexd[b[i] & 0xf];
        }
      }
      // key order and ", "/": " separators match the Python lane's
      // json.dumps(Event.to_json()) byte-for-byte (except raw-spliced
      // props/tags spans, which keep the input's own spacing)
      payload += "{\"op\": \"put\", \"event\": {\"event\": ";
      emit_json_string(payload, r.event.data(), r.event.size());
      payload += ", \"entityType\": ";
      emit_json_string(payload, r.etype.data(), r.etype.size());
      payload += ", \"entityId\": ";
      emit_json_string(payload, r.eid.data(), r.eid.size());
      payload += ", \"eventId\": ";
      emit_json_string(payload, id, idn);
      if (r.has_tt) {
        payload += ", \"targetEntityType\": ";
        emit_json_string(payload, r.ttype.data(), r.ttype.size());
        payload += ", \"targetEntityId\": ";
        emit_json_string(payload, r.tid.data(), r.tid.size());
      }
      if (r.props_n > 0) {
        payload += ", \"properties\": ";
        payload.append(r.props_b,
                       static_cast<size_t>(r.props_e - r.props_b));
      }
      payload += ", \"eventTime\": \"";
      payload += et;
      payload += "\"";
      if (r.tags_nonempty) {
        payload += ", \"tags\": ";
        payload.append(r.tags_b,
                       static_cast<size_t>(r.tags_e - r.tags_b));
      }
      if (r.has_prid) {
        payload += ", \"prId\": ";
        payload.append(r.prid_b,
                       static_cast<size_t>(r.prid_e - r.prid_b));
      }
      payload += ", \"creationTime\": \"";
      payload += ct;
      payload += "\"}}\n";
      ++nev;
      continue;
    }
  fallback:
    fellback = true;
    goto loop_done;
  }
loop_done:;
  Py_END_ALLOW_THREADS;
  if (rand_exhausted) {
    PyErr_SetString(PyExc_ValueError,
                    "import_jsonl: rand buffer exhausted");
    return nullptr;
  }
  if (fellback)
    return Py_BuildValue("(OLL)", Py_None, static_cast<long long>(0),
                         nline);
  PyObject* pb = PyBytes_FromStringAndSize(
      payload.data(), static_cast<Py_ssize_t>(payload.size()));
  if (!pb) return nullptr;
  return Py_BuildValue("(NLL)", pb, nev, static_cast<long long>(0));
}

// pack_flat(rows, cols, vals, row_base, row_cap, n_rows, S)
//   rows/cols: int32 little-endian buffers (nnz entries each),
//   vals: float32 buffer (nnz), row_base/row_cap: int32 (n_rows)
//   -> (idx: bytes of S int32, val: bytes of S float32)
// Host counting-sort scatter with the exact semantics of
// ops/ragged._pack_flat_on_device (stable input order within a row,
// entries beyond row_cap drop, padding slots stay zero) — one linear
// pass instead of a device round-trip: at MovieLens-20M scale the
// jitted pack cost ~35s/side through a remote-compile tunnel
// (program build + ~240MB H2D + ~320MB D2H); this does it in ~1s on
// one core and the flat buffers are already where the bucket carving
// wants them (host).
PyObject* pack_flat(PyObject*, PyObject* args) {
  Py_buffer rows, cols, vals, base, cap;
  long long n_rows, S;
  if (!PyArg_ParseTuple(args, "y*y*y*y*y*LL", &rows, &cols, &vals, &base,
                        &cap, &n_rows, &S))
    return nullptr;
  PyObject* out = nullptr;
  PyObject* idx_b = nullptr;
  PyObject* val_b = nullptr;
  const Py_ssize_t nnz = rows.len / 4;
  if (cols.len != rows.len || vals.len != rows.len ||
      base.len < n_rows * 4 || cap.len < n_rows * 4 || S < 0 ||
      n_rows < 0) {
    PyErr_SetString(PyExc_ValueError, "pack_flat: buffer size mismatch");
    goto done;
  }
  idx_b = PyBytes_FromStringAndSize(nullptr, S * 4);
  val_b = PyBytes_FromStringAndSize(nullptr, S * 4);
  if (!idx_b || !val_b) goto done;
  {
    int32_t* idx = reinterpret_cast<int32_t*>(PyBytes_AS_STRING(idx_b));
    float* val = reinterpret_cast<float*>(PyBytes_AS_STRING(val_b));
    const int32_t* r = static_cast<const int32_t*>(rows.buf);
    const int32_t* c = static_cast<const int32_t*>(cols.buf);
    const float* v = static_cast<const float*>(vals.buf);
    const int32_t* rb = static_cast<const int32_t*>(base.buf);
    const int32_t* rc = static_cast<const int32_t*>(cap.buf);
    bool oob = false;
    Py_BEGIN_ALLOW_THREADS;
    memset(idx, 0, static_cast<size_t>(S) * 4);
    memset(val, 0, static_cast<size_t>(S) * 4);
    std::vector<int32_t> used(static_cast<size_t>(n_rows), 0);
    for (Py_ssize_t k = 0; k < nnz; ++k) {
      const int32_t row = r[k];
      if (row < 0 || row >= n_rows) {
        oob = true;
        break;
      }
      const int32_t u = used[row];
      if (u >= rc[row]) continue;  // capped entry drops (input order)
      const int64_t dest = static_cast<int64_t>(rb[row]) + u;
      if (dest < 0 || dest >= S) {
        oob = true;
        break;
      }
      used[row] = u + 1;
      idx[dest] = c[k];
      val[dest] = v[k];
    }
    Py_END_ALLOW_THREADS;
    if (oob) {
      PyErr_SetString(PyExc_ValueError,
                      "pack_flat: row id or destination out of range");
      goto done;
    }
  }
  out = Py_BuildValue("(OO)", idx_b, val_b);
done:
  Py_XDECREF(idx_b);
  Py_XDECREF(val_b);
  PyBuffer_Release(&rows);
  PyBuffer_Release(&cols);
  PyBuffer_Release(&vals);
  PyBuffer_Release(&base);
  PyBuffer_Release(&cap);
  return out;
}

PyMethodDef methods[] = {
    {"parse_segment", parse_segment, METH_VARARGS,
     "Parse one jsonl event segment into column lists."},
    {"import_jsonl", import_jsonl, METH_VARARGS,
     "Convert API-format JSON lines into a segment payload."},
    {"pack_flat", pack_flat, METH_VARARGS,
     "Counting-sort COO triples into a flat ragged-history buffer."},
    {nullptr, nullptr, 0, nullptr},
};

struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_codec",
    "Native columnar codec for predictionio_tpu event segments.", -1,
    methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__codec(void) { return PyModule_Create(&moduledef); }
