// predictionio_tpu native columnar codec.
//
// The segmentfs event log is JSONL the framework itself writes
// ({"op":"put","event":{...}} / {"op":"del","id":...}); its columnar
// sidecar encode was measured parse-bound (~54k events/s through
// json.loads + dict access on one core). This module parses one whole
// segment buffer in C++ — a full JSON tokenizer (string escapes incl.
// \uXXXX surrogate pairs, nested values) with shallow extraction of the
// bulk-projection fields — and returns plain Python lists ready for the
// existing columnar_from_columns path. Any non-"put" record makes the
// parse return None (the Python caller already rebuilds on deletes).
//
// Build: auto-compiled on first use by predictionio_tpu/native
// (g++ -O2 -shared -fPIC), or `python setup_native.py build_ext`.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <clocale>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <vector>

namespace {

// strtod is locale-dependent (an LC_NUMERIC with a decimal comma would
// misparse "4.5"); parse with a pinned C locale instead.
locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", nullptr);
  return loc;
}

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const char* s, Py_ssize_t n) : p(s), end(s + n) {}

  void fail() { ok = false; }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  }

  bool expect(char c) {
    skip_ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    fail();
    return false;
  }

  bool peek(char c) {
    skip_ws();
    return p < end && *p == c;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out.push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  int hex4() {
    if (end - p < 4) {
      fail();
      return -1;
    }
    int v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else {
        fail();
        return -1;
      }
    }
    p += 4;
    return v;
  }

  // Parse a JSON string (opening quote already expected by caller via
  // expect('"') == false; here we do the full job).
  bool parse_string(std::string& out) {
    out.clear();
    if (!expect('"')) return false;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (p >= end) {
          fail();
          return false;
        }
        char e = *p++;
        switch (e) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'n': out.push_back('\n'); break;
          case 'r': out.push_back('\r'); break;
          case 't': out.push_back('\t'); break;
          case 'u': {
            int u = hex4();
            if (!ok) return false;
            unsigned cp = static_cast<unsigned>(u);
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              // must be a valid surrogate pair; a LONE surrogate (legal
              // to Python's json) has no UTF-8 form — fail so the
              // caller falls back to the Python parser
              if (end - p >= 6 && p[0] == '\\' && p[1] == 'u') {
                p += 2;
                int lo = hex4();
                if (!ok) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else {
                  fail();
                  return false;
                }
              } else {
                fail();
                return false;
              }
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              fail();  // lone low surrogate
              return false;
            }
            append_utf8(out, cp);
            break;
          }
          default:
            fail();
            return false;
        }
      } else {
        out.push_back(c);
      }
    }
    fail();
    return false;
  }

  bool skip_string() {
    if (!expect('"')) return false;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (p >= end) break;
        ++p;
      }
    }
    fail();
    return false;
  }

  bool parse_number(double* out) {
    skip_ws();
    char* endptr = nullptr;
    double v = strtod_l(p, &endptr, c_locale());
    if (endptr == p) {
      fail();
      return false;
    }
    p = endptr;
    if (out) *out = v;
    return true;
  }

  bool skip_value();

  bool skip_object() {
    if (!expect('{')) return false;
    if (peek('}')) {
      ++p;
      return true;
    }
    while (ok) {
      if (!skip_string()) return false;
      if (!expect(':')) return false;
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      return expect('}');
    }
    return false;
  }

  bool skip_array() {
    if (!expect('[')) return false;
    if (peek(']')) {
      ++p;
      return true;
    }
    while (ok) {
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      return expect(']');
    }
    return false;
  }

  bool skip_literal(const char* lit, size_t n) {
    if (static_cast<size_t>(end - p) < n || memcmp(p, lit, n) != 0) {
      fail();
      return false;
    }
    p += n;
    return true;
  }
};

bool Parser::skip_value() {
  skip_ws();
  if (p >= end) {
    fail();
    return false;
  }
  switch (*p) {
    case '"': return skip_string();
    case '{': return skip_object();
    case '[': return skip_array();
    case 't': return skip_literal("true", 4);
    case 'f': return skip_literal("false", 5);
    case 'n': return skip_literal("null", 4);
    default: return parse_number(nullptr);
  }
}

struct Record {
  std::string event, entity_type, entity_id, event_time, event_id;
  std::string target_type, target_id;
  bool has_tt = false, has_ti = false;
  const char* props_start = nullptr;
  const char* props_end = nullptr;
  std::vector<double> fprops;  // parallel to requested names
};

// events-object parser with shallow float-prop extraction
bool parse_event_obj(Parser& ps, Record& rec,
                     const std::vector<std::string>& want) {
  if (!ps.expect('{')) return false;
  rec.fprops.assign(want.size(), NAN);
  if (ps.peek('}')) {
    ++ps.p;
    return true;
  }
  std::string key;
  while (ps.ok) {
    if (!ps.parse_string(key)) return false;
    if (!ps.expect(':')) return false;
    if (key == "event") {
      if (!ps.parse_string(rec.event)) return false;
    } else if (key == "entityType") {
      if (!ps.parse_string(rec.entity_type)) return false;
    } else if (key == "entityId") {
      if (!ps.parse_string(rec.entity_id)) return false;
    } else if (key == "targetEntityType") {
      if (!ps.parse_string(rec.target_type)) return false;
      rec.has_tt = true;
    } else if (key == "targetEntityId") {
      if (!ps.parse_string(rec.target_id)) return false;
      rec.has_ti = true;
    } else if (key == "eventTime") {
      if (!ps.parse_string(rec.event_time)) return false;
    } else if (key == "eventId") {
      if (!ps.parse_string(rec.event_id)) return false;
    } else if (key == "properties") {
      ps.skip_ws();
      rec.props_start = ps.p;
      if (ps.peek('{')) {
        // shallow walk: capture requested numeric props, skip the rest
        ++ps.p;
        if (ps.peek('}')) {
          ++ps.p;
        } else {
          std::string pk;
          while (ps.ok) {
            if (!ps.parse_string(pk)) return false;
            if (!ps.expect(':')) return false;
            ps.skip_ws();
            bool taken = false;
            for (size_t w = 0; w < want.size(); ++w) {
              if (pk == want[w]) {
                // numbers only — bools/strings/null stay NaN.
                // Python's json also emits/accepts the non-standard
                // Infinity/-Infinity/NaN tokens: match it (strtod
                // parses them), else the two paths diverge on inf.
                if (ps.p < ps.end &&
                    (*ps.p == '-' || (*ps.p >= '0' && *ps.p <= '9') ||
                     *ps.p == 'I' || *ps.p == 'N')) {
                  double v;
                  if (!ps.parse_number(&v)) return false;
                  rec.fprops[w] = v;
                } else {
                  if (!ps.skip_value()) return false;
                }
                taken = true;
                break;
              }
            }
            if (!taken && !ps.skip_value()) return false;
            ps.skip_ws();
            if (ps.p < ps.end && *ps.p == ',') {
              ++ps.p;
              continue;
            }
            if (!ps.expect('}')) return false;
            break;
          }
          if (!ps.ok) return false;
        }
      } else {
        if (!ps.skip_value()) return false;
      }
      rec.props_end = ps.p;
    } else {
      if (!ps.skip_value()) return false;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') {
      ++ps.p;
      continue;
    }
    return ps.expect('}');
  }
  return false;
}

PyObject* str_or_die(const std::string& s) {
  return PyUnicode_FromStringAndSize(s.data(),
                                     static_cast<Py_ssize_t>(s.size()));
}

// parse_segment(data: bytes, float_props: tuple[str, ...])
//   -> None                      (a non-"put" record: caller rebuilds)
//    | (event, entity_type, entity_id, target_type, target_id,
//       event_time, event_id, props_raw, fprops_lists)  all lists
PyObject* parse_segment(PyObject*, PyObject* args) {
  const char* buf;
  Py_ssize_t len;
  PyObject* want_tuple;
  if (!PyArg_ParseTuple(args, "y#O!", &buf, &len, &PyTuple_Type,
                        &want_tuple))
    return nullptr;
  std::vector<std::string> want;
  for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(want_tuple); ++i) {
    PyObject* it = PyTuple_GET_ITEM(want_tuple, i);
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(it, &n);
    if (!s) return nullptr;
    want.emplace_back(s, static_cast<size_t>(n));
  }

  std::vector<Record> recs;
  recs.reserve(1024);
  const char* line = buf;
  const char* bend = buf + len;
  std::string key, op, del_id;
  while (line < bend) {
    const char* nl = static_cast<const char*>(
        memchr(line, '\n', static_cast<size_t>(bend - line)));
    const char* lend = nl ? nl : bend;
    bool blank = true;
    for (const char* q = line; q < lend; ++q)
      if (*q != ' ' && *q != '\t' && *q != '\r') {
        blank = false;
        break;
      }
    if (blank) {
      line = nl ? nl + 1 : bend;
      continue;
    }
    Parser ps(line, lend - line);
    Record rec;
    bool got_event = false;
    op.clear();
    if (!ps.expect('{')) goto bad;
    while (ps.ok) {
      if (!ps.parse_string(key)) goto bad;
      if (!ps.expect(':')) goto bad;
      if (key == "op") {
        if (!ps.parse_string(op)) goto bad;
      } else if (key == "event") {
        if (!parse_event_obj(ps, rec, want)) goto bad;
        got_event = true;
      } else if (key == "id") {
        if (!ps.parse_string(del_id)) goto bad;
      } else {
        if (!ps.skip_value()) goto bad;
      }
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == ',') {
        ++ps.p;
        continue;
      }
      if (!ps.expect('}')) goto bad;
      break;
    }
    if (!ps.ok) goto bad;
    if (op != "put") Py_RETURN_NONE;  // deletes: Python path rebuilds
    if (!got_event || rec.event.empty() || rec.entity_type.empty())
      goto bad;
    recs.push_back(std::move(rec));
    line = nl ? nl + 1 : bend;
    continue;
  bad:
    PyErr_Format(PyExc_ValueError,
                 "native codec: malformed segment line at offset %zd",
                 static_cast<Py_ssize_t>(line - buf));
    return nullptr;
  }

  Py_ssize_t n = static_cast<Py_ssize_t>(recs.size());
  PyObject* out = PyTuple_New(9);
  if (!out) return nullptr;
  PyObject* cols[8];
  for (int c = 0; c < 8; ++c) {
    cols[c] = PyList_New(n);
    if (!cols[c]) {
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(out, c, cols[c]);
  }
  PyObject* fcols = PyList_New(static_cast<Py_ssize_t>(want.size()));
  if (!fcols) {
    Py_DECREF(out);
    return nullptr;
  }
  PyTuple_SET_ITEM(out, 8, fcols);
  std::vector<PyObject*> flists(want.size());
  for (size_t w = 0; w < want.size(); ++w) {
    flists[w] = PyList_New(n);
    if (!flists[w]) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(fcols, static_cast<Py_ssize_t>(w), flists[w]);
  }

  for (Py_ssize_t i = 0; i < n; ++i) {
    Record& r = recs[static_cast<size_t>(i)];
    PyObject* v;
    if (!(v = str_or_die(r.event))) goto fail;
    PyList_SET_ITEM(cols[0], i, v);
    if (!(v = str_or_die(r.entity_type))) goto fail;
    PyList_SET_ITEM(cols[1], i, v);
    if (!(v = str_or_die(r.entity_id))) goto fail;
    PyList_SET_ITEM(cols[2], i, v);
    if (r.has_tt) {
      if (!(v = str_or_die(r.target_type))) goto fail;
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    PyList_SET_ITEM(cols[3], i, v);
    if (r.has_ti) {
      if (!(v = str_or_die(r.target_id))) goto fail;
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    PyList_SET_ITEM(cols[4], i, v);
    if (!(v = str_or_die(r.event_time))) goto fail;
    PyList_SET_ITEM(cols[5], i, v);
    if (!(v = str_or_die(r.event_id))) goto fail;
    PyList_SET_ITEM(cols[6], i, v);
    if (r.props_start && r.props_end > r.props_start) {
      v = PyBytes_FromStringAndSize(
          r.props_start,
          static_cast<Py_ssize_t>(r.props_end - r.props_start));
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    if (!v) goto fail;
    PyList_SET_ITEM(cols[7], i, v);
    for (size_t w = 0; w < want.size(); ++w) {
      v = PyFloat_FromDouble(r.fprops[w]);
      if (!v) goto fail;
      PyList_SET_ITEM(flists[w], i, v);
    }
    continue;
  fail:
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

PyMethodDef methods[] = {
    {"parse_segment", parse_segment, METH_VARARGS,
     "Parse one jsonl event segment into column lists."},
    {nullptr, nullptr, 0, nullptr},
};

struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_codec",
    "Native columnar codec for predictionio_tpu event segments.", -1,
    methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__codec(void) { return PyModule_Create(&moduledef); }
