"""Versioned release registry over engine-instance metadata.

Every action that changes which model serves traffic — deploy, reload,
canary start, ramp step, promote, rollback, undeploy, pin — is recorded
as a :class:`ReleaseEvent` (who/when/why), and the current release state
(stable instance, pinned instance, live candidate) is queryable from any
process that shares the storage environment.

Persistence rides the existing storage repos: the state document is a
JSON blob stored through the MODELDATA repository (``storage.models()``)
under a reserved ``__release__`` key — every backend (memory, sqlite,
localfs, segmentfs, remote, objectstore) already implements upsert
``insert``/``get`` for model blobs, so the registry needs no per-backend
DAO. Writes are last-writer-wins per engine triple; the writers are the
deploy-time CLI and the single engine server that owns the triple, so
contention is not a practical concern (same model as the reference's
EngineInstances metadata).
"""

from __future__ import annotations

import hashlib
import json

from ..concurrency import new_rlock
from dataclasses import asdict, dataclass, field
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

from ..data.storage.base import (
    RESERVED_MODEL_KEY_PREFIX as RESERVED_PREFIX,
    STATUS_COMPLETED,
    Model,
)

#: One extra blob lists every engine triple that has release state, so
#: ``ptpu status``/``ptpu release list`` can enumerate without a scan
#: API on ModelsDAO.
INDEX_KEY = RESERVED_PREFIX + "-index"

#: History is capped so the blob stays small on servers that reload
#: every retrain for months; the newest events win.
MAX_HISTORY = 500


def _utcnow_iso() -> str:
    return datetime.now(timezone.utc).isoformat()


@dataclass(frozen=True)
class ReleaseEvent:
    """One recorded release action: who did what, when, and why."""

    seq: int
    time: str
    action: str
    instance_id: str = ""
    actor: str = ""
    reason: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ReleaseEvent":
        return ReleaseEvent(
            seq=int(d.get("seq", 0)), time=d.get("time", ""),
            action=d.get("action", ""),
            instance_id=d.get("instance_id", ""),
            actor=d.get("actor", ""), reason=d.get("reason", ""),
            extra=dict(d.get("extra") or {}))


def _empty_state() -> Dict[str, Any]:
    return {
        "stable": "",          # instance id currently serving 100%
        "previousStable": "",  # what `rollback` reverts to
        "pinned": "",          # deploy/reload bind this instead of latest
        "candidate": "",       # live canary/shadow instance id
        "candidateMode": "",   # "canary" | "shadow" | ""
        "fraction": 0.0,       # candidate traffic fraction
        "seq": 0,
        "history": [],         # ReleaseEvent dicts, oldest first
    }


class ReleaseRegistry:
    """Release state + history for one engine triple
    (engine_id, engine_version, engine_variant)."""

    def __init__(self, storage, engine_id: str,
                 engine_version: str = "1",
                 engine_variant: str = "engine.json"):
        self.storage = storage
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self._lock = new_rlock("ReleaseRegistry._lock")

    # -- persistence --------------------------------------------------------
    @property
    def key(self) -> str:
        """Blob key: hashed so variant paths (slashes, dots) never leak
        into backend path/key grammars."""
        digest = hashlib.sha1(
            "\x00".join((self.engine_id, self.engine_version,
                         self.engine_variant)).encode("utf-8")).hexdigest()
        return f"{RESERVED_PREFIX}-{digest[:20]}"

    def _load(self) -> Dict[str, Any]:
        # ptpu: allow[blocking-under-lock] — the registry lock IS the
        # read-modify-write atomicity boundary for the release blob:
        # every caller holds it across load+mutate+save by design.
        # Admin-plane ops (pin/promote/rollback), never the query path.
        blob = self.storage.models().get(self.key)
        if blob is None:
            return _empty_state()
        try:
            state = json.loads(blob.models.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return _empty_state()
        merged = _empty_state()
        merged.update(state)
        return merged

    def _save(self, state: Dict[str, Any]) -> None:
        state["history"] = state["history"][-MAX_HISTORY:]
        payload = json.dumps(state).encode("utf-8")
        # ptpu: allow[blocking-under-lock] — same contract as _load:
        # the held lock is what makes load+mutate+save atomic
        self.storage.models().insert(Model(id=self.key, models=payload))
        self._index_self()

    def _index_self(self) -> None:
        triple = [self.engine_id, self.engine_version, self.engine_variant]
        # ptpu: allow[blocking-under-lock] — rides _save's atomicity
        # contract (see _load); index writes are admin-plane only
        models = self.storage.models()
        blob = models.get(INDEX_KEY)
        entries: List[List[str]] = []
        if blob is not None:
            try:
                entries = json.loads(blob.models.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                entries = []
        if triple not in entries:
            entries.append(triple)
            models.insert(Model(
                id=INDEX_KEY,
                models=json.dumps(entries).encode("utf-8")))

    @staticmethod
    def list_tracked(storage) -> List[Tuple[str, str, str]]:
        """Every engine triple with recorded release state."""
        blob = storage.models().get(INDEX_KEY)
        if blob is None:
            return []
        try:
            entries = json.loads(blob.models.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return []
        return [tuple(e) for e in entries if len(e) == 3]

    # -- reads --------------------------------------------------------------
    def state(self) -> Dict[str, Any]:
        """Current release state WITHOUT the history list."""
        with self._lock:
            st = self._load()
        st.pop("history", None)
        return st

    def history(self, limit: Optional[int] = None) -> List[ReleaseEvent]:
        """Recorded events, oldest first (``limit`` keeps the newest)."""
        with self._lock:
            raw = self._load()["history"]
        if limit is not None:
            raw = raw[-limit:]
        return [ReleaseEvent.from_json(d) for d in raw]

    def pinned_instance(self) -> Optional[str]:
        """The pinned instance id, or None — deploy/reload honor this
        over get_latest_completed."""
        pinned = self.state().get("pinned") or ""
        return pinned or None

    def to_json(self, history_limit: int = 50) -> Dict[str, Any]:
        """The ``/release.json`` payload body."""
        with self._lock:
            st = self._load()
        history = st.pop("history", [])[-history_limit:]
        return {
            "engineId": self.engine_id,
            "engineVersion": self.engine_version,
            "engineVariant": self.engine_variant,
            "state": st,
            "history": history,
        }

    # -- writes -------------------------------------------------------------
    def _record_locked(self, state: Dict[str, Any], action: str,
                       instance_id: str = "", actor: str = "",
                       reason: str = "",
                       **extra: Any) -> ReleaseEvent:
        state["seq"] = int(state.get("seq", 0)) + 1
        ev = ReleaseEvent(seq=state["seq"], time=_utcnow_iso(),
                          action=action, instance_id=instance_id,
                          actor=actor, reason=reason, extra=dict(extra))
        state["history"].append(ev.to_json())
        return ev

    def record(self, action: str, instance_id: str = "", actor: str = "",
               reason: str = "", **extra: Any) -> ReleaseEvent:
        """Append a history event without changing release state
        (e.g. ``undeploy``, ``shadow-window``)."""
        with self._lock:
            state = self._load()
            ev = self._record_locked(state, action, instance_id, actor,
                                     reason, **extra)
            self._save(state)
        return ev

    def _require_completed(self, instance_id: str) -> None:
        inst = self.storage.engine_instances().get(instance_id)
        if inst is None:
            raise ValueError(f"engine instance {instance_id!r} not found")
        if inst.status != STATUS_COMPLETED:
            raise ValueError(
                f"engine instance {instance_id!r} is {inst.status}, "
                f"not {STATUS_COMPLETED}")

    def record_deploy(self, instance_id: str, actor: str = "",
                      reason: str = "") -> ReleaseEvent:
        """A deploy (or reload) bound ``instance_id`` as the serving
        stable."""
        with self._lock:
            state = self._load()
            if state["stable"] and state["stable"] != instance_id:
                state["previousStable"] = state["stable"]
            state["stable"] = instance_id
            ev = self._record_locked(state, "deploy", instance_id, actor,
                                     reason)
            self._save(state)
        return ev

    def pin(self, instance_id: str, actor: str = "",
            reason: str = "") -> ReleaseEvent:
        """Pin deploy/reload to ``instance_id`` (must be COMPLETED)."""
        self._require_completed(instance_id)
        with self._lock:
            state = self._load()
            state["pinned"] = instance_id
            ev = self._record_locked(state, "pin", instance_id, actor,
                                     reason)
            self._save(state)
        return ev

    def unpin(self, actor: str = "", reason: str = "") -> ReleaseEvent:
        with self._lock:
            state = self._load()
            was = state["pinned"]
            state["pinned"] = ""
            ev = self._record_locked(state, "unpin", was, actor, reason)
            self._save(state)
        return ev

    def start_candidate(self, instance_id: str, fraction: float,
                        mode: str = "canary", actor: str = "",
                        reason: str = "") -> ReleaseEvent:
        """A canary/shadow candidate started at ``fraction``."""
        self._require_completed(instance_id)
        with self._lock:
            state = self._load()
            state["candidate"] = instance_id
            state["candidateMode"] = mode
            state["fraction"] = float(fraction)
            ev = self._record_locked(state, mode, instance_id, actor,
                                     reason, fraction=float(fraction))
            self._save(state)
        return ev

    def set_fraction(self, fraction: float, actor: str = "",
                     reason: str = "") -> ReleaseEvent:
        """A ramp step moved the candidate to ``fraction``."""
        with self._lock:
            state = self._load()
            state["fraction"] = float(fraction)
            ev = self._record_locked(state, "ramp", state["candidate"],
                                     actor, reason,
                                     fraction=float(fraction))
            self._save(state)
        return ev

    def promote(self, instance_id: str, actor: str = "",
                reason: str = "") -> ReleaseEvent:
        """``instance_id`` becomes the pinned stable (candidate cleared
        when it was the candidate)."""
        with self._lock:
            state = self._load()
            prior = state["stable"]
            if prior and prior != instance_id:
                state["previousStable"] = prior
            state["stable"] = instance_id
            state["pinned"] = instance_id
            if state["candidate"] == instance_id:
                state["candidate"] = ""
                state["candidateMode"] = ""
                state["fraction"] = 0.0
            ev = self._record_locked(state, "promote", instance_id, actor,
                                     reason, previous_stable=prior)
            self._save(state)
        return ev

    def rollback(self, actor: str = "", reason: str = "") -> ReleaseEvent:
        """Abort the live candidate; with no candidate, revert stable to
        ``previousStable`` (re-pinning it so reload binds it)."""
        with self._lock:
            state = self._load()
            if state["candidate"]:
                was = state["candidate"]
                state["candidate"] = ""
                state["candidateMode"] = ""
                state["fraction"] = 0.0
                ev = self._record_locked(state, "rollback", was, actor,
                                         reason, kind="candidate")
            elif state["previousStable"]:
                was = state["stable"]
                state["stable"] = state["previousStable"]
                state["pinned"] = state["previousStable"]
                state["previousStable"] = ""
                ev = self._record_locked(
                    state, "rollback", was, actor, reason,
                    kind="stable", reverted_to=state["stable"])
            else:
                raise ValueError(
                    "nothing to roll back: no live candidate and no "
                    "previous stable recorded")
            self._save(state)
        return ev
