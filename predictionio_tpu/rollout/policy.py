"""The release health gate: candidate vs. stable over a sliding window.

The controller feeds :meth:`HealthPolicy.evaluate` one
:class:`ArmWindow` per arm — windowed deltas of the engine server's
per-arm release counters and latency histograms (the obs subsystem's
cumulative series diffed against the window-start snapshot). The policy
answers ``advance`` / ``hold`` / ``rollback``; the ramp schedule and the
windows themselves live here so ``ptpu release`` and the tests share
one definition.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from ..obs.histogram import window_quantile

__all__ = ["ArmWindow", "Decision", "HealthPolicy", "DEFAULT_RAMP",
           "window_quantile"]

#: The default promotion ladder (ISSUE: 1% → 5% → 25% → 100%).
DEFAULT_RAMP: Tuple[float, ...] = (0.01, 0.05, 0.25, 1.0)


@dataclass(frozen=True)
class ArmWindow:
    """What one arm did inside the current evaluation window."""

    queries: int = 0
    errors: int = 0
    p99: Optional[float] = None  # seconds; None below min sample

    @property
    def error_rate(self) -> float:
        return self.errors / self.queries if self.queries else 0.0

    def to_json(self) -> dict:
        return {"queries": self.queries, "errors": self.errors,
                "errorRate": round(self.error_rate, 4),
                "p99Sec": self.p99}


@dataclass(frozen=True)
class Decision:
    """The gate's verdict for one window."""

    action: str  # "advance" | "hold" | "rollback"
    reason: str

    def to_json(self) -> dict:
        return {"action": self.action, "reason": self.reason}


@dataclass(frozen=True)
class HealthPolicy:
    """Gate thresholds + ramp schedule (all windows are wall-clock)."""

    #: Candidate traffic fractions walked on consecutive healthy
    #: windows; reaching the final step promotes.
    ramp: Sequence[float] = DEFAULT_RAMP
    #: Seconds per evaluation window.
    window_sec: float = 30.0
    #: Candidate queries required before the gate judges (an idle
    #: canary holds, it neither promotes nor rolls back).
    min_queries: int = 20
    #: Absolute candidate error-rate ceiling.
    max_error_rate: float = 0.05
    #: Candidate error rate may exceed stable's by at most this much
    #: (catches "stable is also erroring" baselines).
    error_rate_slack: float = 0.02
    #: Candidate p99 must stay under stable p99 × this multiple
    #: (only judged when both arms have a full sample).
    p99_regression: float = 2.0

    def next_fraction(self, fraction: float) -> Optional[float]:
        """The ramp step after ``fraction``; None when the ladder is
        exhausted (i.e. the next healthy window promotes)."""
        for step in self.ramp:
            if step > fraction + 1e-9:
                return step
        return None

    def evaluate(self, stable: ArmWindow,
                 candidate: ArmWindow) -> Decision:
        if candidate.queries < self.min_queries:
            return Decision(
                "hold",
                f"insufficient candidate sample "
                f"({candidate.queries}/{self.min_queries} queries)")
        if candidate.error_rate > self.max_error_rate:
            return Decision(
                "rollback",
                f"candidate error rate {candidate.error_rate:.3f} "
                f"exceeds ceiling {self.max_error_rate:.3f} "
                f"({candidate.errors}/{candidate.queries})")
        if stable.queries >= self.min_queries and \
                candidate.error_rate > (stable.error_rate
                                        + self.error_rate_slack):
            return Decision(
                "rollback",
                f"candidate error rate {candidate.error_rate:.3f} "
                f"exceeds stable {stable.error_rate:.3f} + slack "
                f"{self.error_rate_slack:.3f}")
        if (candidate.p99 is not None and stable.p99 is not None
                and stable.queries >= self.min_queries
                and stable.p99 > 0
                and candidate.p99 > stable.p99 * self.p99_regression):
            return Decision(
                "rollback",
                f"candidate p99 {candidate.p99 * 1000:.1f}ms exceeds "
                f"stable {stable.p99 * 1000:.1f}ms × "
                f"{self.p99_regression:g}")
        return Decision(
            "advance",
            f"healthy window: {candidate.queries} queries, error rate "
            f"{candidate.error_rate:.3f}")

    def to_json(self) -> dict:
        return {"ramp": list(self.ramp), "windowSec": self.window_sec,
                "minQueries": self.min_queries,
                "maxErrorRate": self.max_error_rate,
                "errorRateSlack": self.error_rate_slack,
                "p99Regression": self.p99_regression}
