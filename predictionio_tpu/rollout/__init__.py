"""Progressive delivery: versioned releases, canary/shadow traffic,
health-gated auto-promotion and auto-rollback.

The deploy story of the reference was binary — ``pio deploy`` bound one
COMPLETED engine instance and ``/reload`` flipped 100% of traffic to
the newest blob in one step. This subsystem makes every model that
reaches traffic a *recorded, reversible release*:

- :mod:`.registry` — a versioned release registry layered over
  engine-instance metadata (pin, promote, rollback, history with
  who/when/why), persisted through the existing storage repos.
- :mod:`.splitter` — a deterministic traffic splitter for the
  QueryServer hot path: hash-of-entity cohorts route a configurable
  fraction of queries to a *candidate* instance bound alongside the
  stable one, plus a shadow mode that mirrors queries without
  returning the candidate's answers.
- :mod:`.policy` — the health gate: candidate vs. stable error rate
  and serve-phase p99 over a sliding window.
- :mod:`.controller` — the loop that ramps a healthy candidate
  (1% → 5% → 25% → 100%), promotes it to the pinned stable, or
  auto-rolls-back an unhealthy one.

Wired through ``ptpu release {list,show,pin,promote,rollback,canary,
status}`` and the engine server's ``/release.json`` +
``/release/{canary,promote,rollback}`` routes. See
docs/deployment.md "Release lifecycle".
"""

from .controller import RolloutController
from .policy import ArmWindow, Decision, HealthPolicy, window_quantile
from .registry import ReleaseEvent, ReleaseRegistry
from .splitter import TrafficSplitter, cohort_bucket

__all__ = [
    "ArmWindow",
    "Decision",
    "HealthPolicy",
    "ReleaseEvent",
    "ReleaseRegistry",
    "RolloutController",
    "TrafficSplitter",
    "cohort_bucket",
    "window_quantile",
]
