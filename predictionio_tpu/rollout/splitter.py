"""Deterministic traffic splitter for the serving hot path.

Routing is by **hash-of-entity cohort**, not per-request randomness: the
same user/entity lands on the same arm for the whole rollout, so a
canary's behavior change is coherent per user (and A/A comparisons are
not diluted by per-request flapping). The cohort is monotone under
ramping — the set of entities routed to the candidate at fraction f1 is
a subset of the set at f2 > f1 — so every ramp step only ADDS cohort,
it never churns users between arms.

The hot-path cost is one sha256 over a short string per query; no
locks (fraction reads are a single attribute load).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional, Sequence

ARM_STABLE = "stable"
ARM_CANDIDATE = "candidate"

#: Query-dict fields tried (in order) as the cohort entity key. Covers
#: the bundled templates (user-keyed recommendation/ecommerce/seqrec,
#: item-keyed similarproduct) without engine-specific config.
DEFAULT_COHORT_FIELDS: Sequence[str] = (
    "user", "userId", "entityId", "entity_id", "uid", "item", "items")


def cohort_bucket(key: str) -> float:
    """Map a cohort key to a uniform bucket in [0, 1) — stable across
    processes and python versions (sha256, not ``hash()``)."""
    digest = hashlib.sha256(key.encode("utf-8", "surrogatepass")).digest()
    return int.from_bytes(digest[:8], "big") / 2.0 ** 64


class TrafficSplitter:
    """Routes queries between the stable and candidate arms.

    ``fraction`` is the share of cohort space routed to the candidate
    (0.0 = none, 1.0 = all). ``shadow=True`` means the fraction selects
    queries to *mirror* — the stable arm still answers all of them.
    """

    def __init__(self, fraction: float = 0.0, shadow: bool = False,
                 cohort_fields: Sequence[str] = DEFAULT_COHORT_FIELDS):
        self.fraction = float(fraction)
        self.shadow = bool(shadow)
        self.cohort_fields = tuple(cohort_fields)

    def set_fraction(self, fraction: float) -> None:
        self.fraction = min(max(float(fraction), 0.0), 1.0)

    def cohort_key(self, query_json: Any) -> str:
        """The entity identity this query is bucketed by; falls back to
        the whole (canonicalized) query for entity-less queries so the
        split stays deterministic."""
        if isinstance(query_json, dict):
            for name in self.cohort_fields:
                v = query_json.get(name)
                if v is not None and not isinstance(v, (dict, list)):
                    return f"{name}={v}"
        try:
            return json.dumps(query_json, sort_keys=True, default=str)
        except (TypeError, ValueError):
            return str(query_json)

    def routes_candidate(self, query_json: Any) -> bool:
        """True when this query's cohort falls inside the candidate
        fraction. Monotone in ``fraction``: bucket < f1 implies
        bucket < f2 for f2 > f1."""
        f = self.fraction
        if f <= 0.0:
            return False
        if f >= 1.0:
            return True
        return cohort_bucket(self.cohort_key(query_json)) < f

    def route(self, query_json: Any) -> str:
        """``"candidate"`` or ``"stable"`` for a canary split (shadow
        callers use :meth:`routes_candidate` to pick mirrors — the
        stable arm answers regardless)."""
        return (ARM_CANDIDATE if not self.shadow
                and self.routes_candidate(query_json) else ARM_STABLE)

    def describe(self) -> dict:
        return {"fraction": self.fraction, "shadow": self.shadow}


def parse_fraction(value: Any, default: Optional[float] = None) -> float:
    """Parse a traffic fraction from user input (CLI/HTTP): accepts
    0.05, "0.05", or "5%"; validates (0, 1]."""
    if value is None:
        if default is None:
            raise ValueError("fraction required")
        return default
    s = str(value).strip()
    if s.endswith("%"):
        f = float(s[:-1]) / 100.0
    else:
        f = float(s)
    if not 0.0 < f <= 1.0:
        raise ValueError(f"fraction must be in (0, 1], got {value!r}")
    return f
