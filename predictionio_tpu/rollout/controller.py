"""The rollout controller: health-gated ramp / rollback loop.

One daemon thread per live candidate. Each ``policy.window_sec`` it
diffs the engine server's per-arm release series (queries, errors,
latency buckets) against the window-start snapshot, asks the
:class:`~.policy.HealthPolicy` for a verdict, and acts:

- ``advance`` → step the splitter up the ramp (1% → 5% → 25% → 100%);
  past the last step the candidate is promoted: the server rebinds it
  as the stable release and the registry pins it.
- ``rollback`` → the candidate is unbound (stable keeps serving — it
  never stopped) and the registry records why.
- ``hold`` → keep the window open (the sample keeps accumulating) —
  an idle canary neither promotes nor rolls back.

Shadow mode never auto-promotes or auto-rolls-back: mirrored answers
are discarded, so candidate errors cost no user traffic; the gate's
verdicts are recorded per window for the operator to act on
(``ptpu release promote``).

Everything the loop decides is observable: ``pio_release_*`` gauges
and counters on the server's registry, the ``/release.json`` endpoint,
and registry history entries with the gate's reason strings.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

from ..concurrency import new_lock
from .policy import ArmWindow, Decision, HealthPolicy, window_quantile
from .registry import ReleaseRegistry
from .splitter import ARM_CANDIDATE, ARM_STABLE, TrafficSplitter

log = logging.getLogger(__name__)


class RolloutController:
    """Owns one candidate's progressive-delivery lifecycle."""

    def __init__(self, server: Any, registry: ReleaseRegistry,
                 instance_id: str,
                 policy: Optional[HealthPolicy] = None,
                 fraction: Optional[float] = None,
                 shadow: bool = False, actor: str = ""):
        self.server = server
        self.registry = registry
        self.instance_id = instance_id
        self.policy = policy or HealthPolicy()
        self.shadow = shadow
        self.actor = actor or "rollout-controller"
        start_fraction = (fraction if fraction is not None
                          else (1.0 if shadow else self.policy.ramp[0]))
        self.splitter = TrafficSplitter(start_fraction, shadow=shadow)
        self._stop = threading.Event()
        self._lock = new_lock("RolloutController._lock")
        self.active = True
        self.outcome = ""      # "" while live; "promoted" | "rolled_back"
        self.windows = 0
        self.last_decision: Optional[Decision] = None
        self.last_windows: Dict[str, dict] = {}
        self._baseline = {arm: server.release_arm_snapshot(arm)
                          for arm in (ARM_STABLE, ARM_CANDIDATE)}
        self._register_metrics()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rollout-controller")

    # -- metrics ------------------------------------------------------------
    def _register_metrics(self) -> None:
        reg = self.server.metrics
        reg.gauge(
            "pio_release_canary_fraction",
            "Traffic fraction routed (canary) or mirrored (shadow) to "
            "the candidate release",
            # ptpu: guarded-by[_lock] — scrape-time gauge snapshot of a
            # bool flag: the read is atomic in CPython and a stale
            # sample for one scrape interval is what a gauge tolerates
            fn=lambda: self.splitter.fraction if self.active else 0.0)
        reg.gauge(
            "pio_release_rollout_active",
            "1 while a candidate release is bound and health-gated",
            # ptpu: guarded-by[_lock] — same scrape-snapshot argument
            fn=lambda: 1.0 if self.active else 0.0)
        reg.gauge(
            "pio_release_shadow_mode",
            "1 when the live rollout mirrors instead of splitting",
            # ptpu: guarded-by[_lock] — same scrape-snapshot argument
            fn=lambda: 1.0 if (self.active and self.shadow) else 0.0)
        self._promotions = reg.counter(
            "pio_release_promotions_total",
            "Candidates promoted to stable (auto or forced)")
        self._rollbacks = reg.counter(
            "pio_release_rollbacks_total",
            "Candidates rolled back (health gate or operator)")
        self._ramp_steps = reg.counter(
            "pio_release_ramp_steps_total",
            "Healthy windows that stepped the canary fraction up")
        self._windows_total = reg.counter(
            "pio_release_gate_windows_total",
            "Health-gate windows evaluated, by verdict")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "RolloutController":
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the loop without touching bindings (server shutdown).
        Joins the gate thread so a stop→start cycle never leaves a
        stale evaluator ticking (guarded: ``_tick`` outcomes may call
        ``stop`` from the gate thread itself)."""
        with self._lock:
            self.active = False
        self._stop.set()
        t = self._thread
        if t.is_alive() and t is not threading.current_thread():
            t.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.policy.window_sec):
            try:
                if not self._tick():
                    return
            except Exception as e:  # noqa: BLE001 — the gate must not die
                log.error("rollout gate window failed: %s", e)

    def _arm_window(self, arm: str) -> ArmWindow:
        queries, errors, buckets = self.server.release_arm_snapshot(arm)
        b_queries, b_errors, b_buckets = self._baseline[arm]
        return ArmWindow(
            queries=int(queries - b_queries),
            errors=int(errors - b_errors),
            p99=window_quantile(b_buckets, buckets, 0.99))

    def _reset_baseline(self) -> None:
        self._baseline = {arm: self.server.release_arm_snapshot(arm)
                          for arm in (ARM_STABLE, ARM_CANDIDATE)}

    def _tick(self) -> bool:
        """One gate window; returns False when the rollout concluded."""
        with self._lock:
            if not self.active:
                return False
            stable = self._arm_window(ARM_STABLE)
            candidate = self._arm_window(ARM_CANDIDATE)
            decision = self.policy.evaluate(stable, candidate)
            self.windows += 1
            windows = self.windows
            self.last_decision = decision
            self.last_windows = {"stable": stable.to_json(),
                                 "candidate": candidate.to_json()}
            self._windows_total.labels(verdict=decision.action).inc()
        if decision.action == "rollback" and not self.shadow:
            self.rollback(decision.reason)
            return False
        if decision.action == "advance":
            if self.shadow:
                # record the healthy window; the operator promotes
                self.registry.record(
                    "shadow-window", self.instance_id, self.actor,
                    decision.reason, windows=windows)
                self._reset_baseline()
                return True
            nxt = self.policy.next_fraction(self.splitter.fraction)
            if nxt is None:
                self.promote(decision.reason)
                return False
            self.splitter.set_fraction(nxt)
            self._ramp_steps.inc()
            self.registry.set_fraction(nxt, self.actor, decision.reason)
            log.info("release %s ramped to %.0f%%: %s",
                     self.instance_id, nxt * 100, decision.reason)
            self._reset_baseline()
        # hold: window stays open, sample keeps accumulating
        return True

    # -- terminal transitions (also callable by the operator routes) --------
    def promote(self, reason: str) -> None:
        """Candidate becomes the pinned stable; the server rebinds it."""
        with self._lock:
            if not self.active:
                return
            self.active = False
            self.outcome = "promoted"
        self._stop.set()
        self.server.promote_candidate()
        self._promotions.inc()
        try:
            self.registry.promote(self.instance_id, self.actor, reason)
        except Exception as e:  # noqa: BLE001 — serving already switched
            log.error("release history write failed on promote: %s", e)
        log.info("release %s promoted to stable: %s",
                 self.instance_id, reason)

    def rollback(self, reason: str) -> None:
        """Unbind the candidate; stable keeps serving untouched."""
        with self._lock:
            if not self.active:
                return
            self.active = False
            self.outcome = "rolled_back"
        self._stop.set()
        self.server.drop_candidate()
        self._rollbacks.inc()
        try:
            self.registry.rollback(self.actor, reason)
        except Exception as e:  # noqa: BLE001 — candidate already gone
            log.error("release history write failed on rollback: %s", e)
        log.warning("release %s rolled back: %s", self.instance_id, reason)

    # -- observability ------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": self.active,
                "outcome": self.outcome,
                "candidateInstanceId": self.instance_id,
                "mode": "shadow" if self.shadow else "canary",
                # mesh-wide serving (ISSUE 6): which placement the
                # candidate bound under — a sharded stable binds its
                # candidate row-sharded too, and promote re-places
                # through the server's normal _bind (warm-swap, never
                # an inherited half-placement)
                "servingMode": getattr(self.server,
                                       "serving_mode_resolved",
                                       "single"),
                "fraction": self.splitter.fraction,
                "windowsEvaluated": self.windows,
                "lastDecision": (self.last_decision.to_json()
                                 if self.last_decision else None),
                "lastWindows": self.last_windows,
                "policy": self.policy.to_json(),
            }
